"""Continuous-batching inference engine over a fixed slot pool.

The per-request path (``GPTModel.generate``) decodes one request per
dispatch: whenever a request finishes early, the compiled decode loop
idles until the next request arrives, and short requests serialize
behind long ones.  This engine instead runs ONE jitted one-token decode
step over a fixed pool of ``num_slots`` batch rows (the TPU-shaped
continuous batching: slot count and cache length are static, so a
single XLA program serves every tick), admitting queued requests into
slots the moment they free up:

  tick:  admit(queue -> free slots, prefill each)  ->
         one slot-batched decode dispatch          ->
         sample per live slot, evict on EOS/max_new_tokens

Sampling runs ON DEVICE inside the decode dispatch by default
(``sample_mode="device"``: traced per-slot params, seed+counter keys,
device-resident cursors — the tick downloads [B] ids, not [B, V]
logits); ``sample_mode="host"`` keeps the legacy per-slot numpy
sampling on downloaded logits.

Each slot row computes exactly what a B=1 ``GPTAttention.decode`` at
that slot's position computes (see ``decode_slots``), so under greedy
decoding the engine's outputs are token-identical to per-request
``generate()`` — tests/test_serving.py asserts it.

Observability rides on paddle_tpu.monitor: queue depth, slot occupancy,
tokens/sec, TTFT/TPOT histograms — scrape them through
``monitor.render_prometheus()`` or the serving.httpd endpoint.
"""
from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque
from contextlib import nullcontext

import numpy as np

from .. import monitor
from .kvcache import (BlockPool, KVDtypeMismatch, PrefixCache,
                      export_blocks, import_blocks,
                      per_shard_block_bytes)
from .lora import AdapterRegistry, LoRAAdapter, UnknownAdapter
from .request import (MAX_SEED, DeadlineShed, QueueFull, RateLimited,
                      Request, RequestQueue, TenantPolicy, TokenBucket)
from .scheduler import Scheduler


class Migrated(RuntimeError):
    """The request was handed off to another replica mid-stream (KV
    block migration) — the terminal verdict its waiter receives on the
    SOURCE engine.  ``emitted`` is the token prefix generated here
    before the handoff (never lost: a holder that cannot complete the
    migration can always fail over with prompt + emitted as context).
    ``payload`` is the full migration payload when ``migrate_out(...,
    deliver="error")`` routed it through the waiter (the waiter owns
    the import), else None (some other holder owns the payload and
    this waiter may only salvage ``emitted``)."""

    def __init__(self, msg, payload=None, emitted=None):
        super().__init__(msg)
        self.payload = payload
        self.emitted = list(emitted or [])


class _MigrateDemand:
    """One cross-thread migration order (export / import / prefix
    warm), registered by any thread and SERVICED BY THE ENGINE THREAD
    at a tick boundary — the same single-writer discipline as every
    other pool/slot mutation, so migration never races a dispatch."""

    __slots__ = ("kind", "args", "done", "result", "error",
                 "registered_at", "waiting")

    def __init__(self, kind, **args):
        self.kind = kind      # "out" | "in" | "prefix_out" | "prefix_in"
        self.args = args
        self.done = threading.Event()
        self.result = None
        self.error = None
        self.registered_at = time.monotonic()
        self.waiting = False  # an "out" whose target is not yet
        #   exportable: retried every tick, but must not keep an
        #   idle engine's loop spinning (the submit that makes it
        #   actionable wakes the loop anyway)

    def complete(self, result):
        self.result = result
        self.done.set()

    def fail(self, error):
        self.error = error
        self.done.set()

    def wait(self, timeout=None):
        if not self.done.wait(timeout):
            raise TimeoutError(
                f"migration {self.kind} demand: no verdict after "
                f"{timeout}s (engine not stepping?)")
        if self.error is not None:
            raise self.error
        return self.result


def _softmax_np(x):
    x = x - x.max()
    e = np.exp(x)
    return e / e.sum()


def _filter_logits_np(row, temperature, top_k, top_p):
    """Host-side twin of GPTModel._filter_logits for per-slot sampling
    (each slot needs its own rng stream; greedy slots never call this)."""
    row = row.astype(np.float64)
    if temperature != 1.0:
        row = row / temperature
    if top_k and top_k > 0:
        kth = np.sort(row)[-min(top_k, len(row))]
        row = np.where(row < kth, -1e9, row)
    if top_p < 1.0:
        p_eff = max(float(top_p), 1e-9)
        srt = np.sort(row)[::-1]
        probs = _softmax_np(srt)
        cum = np.cumsum(probs)
        keep = (cum - probs) < p_eff
        cutoff = srt[keep].min()
        row = np.where(row < cutoff, -1e9, row)
    return row


class _InflightTick:
    """One dispatched-but-not-consumed decode tick (the async engine
    loop's pipeline entry).  Holds the device handles of the arrays
    the consume side will materialize (ids/done — spec: picks/counts),
    the slot->request bindings AS OF DISPATCH TIME (a slot may be
    evicted and even re-admitted before this tick is consumed; the
    identity check ``slot.request is req`` is what keeps a frozen
    lane's garbage out of a newer request's stream), and a small host
    snapshot of the cursor buffer the dispatch chained from — the
    flight recorder's view of the in-flight ring."""

    __slots__ = ("tick", "kind", "slots", "reqs", "arrays", "batch",
                 "layout", "dispatched_at", "cursors", "spec_lanes",
                 "meta_lanes")

    def __init__(self, tick, kind, slots, arrays, batch, layout,
                 cursors, spec_lanes=None, meta_lanes=None):
        self.tick = tick
        self.kind = kind              # "decode" | "spec" | "ragged"
        self.slots = slots
        self.reqs = [s.request for s in slots]
        self.arrays = arrays          # name -> un-materialized device
        self.batch = batch            # handle (jax async dispatch)
        self.layout = layout
        self.dispatched_at = time.monotonic()
        self.cursors = cursors        # host view of the chained-from
        #   state buffer (flight recorder)
        self.spec_lanes = spec_lanes  # per-slot REAL draft lanes as
        #   of dispatch (consume must not re-read the slot: it may
        #   have been rebound by then)
        self.meta_lanes = meta_lanes  # ragged dispatch: per listed
        #   slot (mode, width, lanes) as of dispatch — same
        #   must-not-re-read rule as spec_lanes

    def meta(self):
        """JSON-able metadata for the flight recorder / debug
        surface (never materializes the device arrays — a dump must
        not block on, or mask, a wedged dispatch)."""
        return {
            "tick": self.tick, "kind": self.kind, "batch": self.batch,
            "layout": self.layout,
            "slots": [s.index for s in self.slots],
            "requests": [r.id for r in self.reqs],
            "in_flight_ms": round(
                (time.monotonic() - self.dispatched_at) * 1e3, 3),
            "cursors": self.cursors,
        }


class Engine:
    """In-process continuous-batching engine for a GPT-family model.

    Parameters
    ----------
    model : GPTModel (eval'd; ``scan_layers`` models serve through
        their auto-synced unrolled decode twin, like ``generate``).
    num_slots : fixed batch-slot pool size (the compiled tick's B).
    max_seq_len : per-slot KV cache length L (prompt + generated must
        fit); defaults to the model's max_position.
    max_queue : admission queue bound (0 = unbounded); a full queue
        sheds load at ``submit`` with QueueFull.
    prefill_buckets : bound prefill compiles under varied traffic.
        ``None`` (default) compiles one prefill program per DISTINCT
        prompt length — fine for tests/benchmarks with few lengths,
        but production traffic with arbitrary lengths would thrash the
        8-entry program cache and stall every slot on each new-length
        compile.  ``"pow2"`` right-pads prompts up to power-of-two
        bucket lengths (plus max_seq_len); an iterable of ints uses
        those bucket lengths.  Right-padding is parity-safe: causal
        attention keeps positions < s independent of the pad tail, the
        true last-token logits are sliced at s-1, and the garbage cache
        rows past s are each overwritten by decode before any query can
        see them.
    kv_block_size : enable the PAGED KV cache (serving/kvcache.py).
        ``None`` (default) keeps the contiguous per-slot rows; an int
        (must divide max_seq_len) carves the pools into fixed-size
        blocks that slots address through block tables — identical
        prompt prefixes share physical blocks, and admission adopts
        cached prefixes so prefill skips the shared span entirely.
        Greedy outputs stay token-identical to the contiguous path
        (same f32 score math over the gathered rows); on TPU a
        near-tie logit may round differently between donor and adopter
        prefill shapes — the same cross-shape caveat as speculative
        decode.  Not combinable with prefill_buckets (the paged
        prefill compiles per (context, tail) length instead).
    kv_blocks : physical block count of the paged pool (default:
        ``num_slots * max_seq_len / kv_block_size`` — the same HBM as
        the contiguous layout; prefix sharing then YIELDS headroom
        that cached prefixes occupy rent-free).  Admission reserves a
        request's worst-case blocks up front, so decode never
        allocates; when the pool cannot cover a request even after LRU
        eviction of unreferenced prefixes, it simply waits in queue.
    prefix_cache : keep finished prompts' full blocks resident in a
        token-trie so later requests adopt them (paged mode only;
        default True).  ``False`` pages without reuse — the A/B
        baseline for the parity tests and bench.
    prefill_chunk : enable BUDGETED CHUNKED PREFILL.  ``None``
        (default) prefills each admitted prompt whole, inline, before
        the tick's decode dispatch — one long prompt then stalls token
        emission for every decoding slot by its full prefill time.  An
        int (must divide max_seq_len) splits each prompt into
        fixed-size chunks run through ONE compiled chunk program
        (bounded compiles, like prefill_buckets); each tick spends at
        most ``tick_token_budget`` prompt tokens on chunks —
        round-robin across PREFILLING slots, resuming partially
        prefilled prompts before starting new ones — and then always
        runs the decode tick for the DECODING slots, so decode latency
        is bounded by the budget, not the longest queued prompt.
        Half-prefilled slots are excluded from decode and sampling
        until their final chunk emits the first token.  Greedy outputs
        stay token-identical to the unchunked engine and to
        ``generate()`` (same caveat as bucketed prefill: on TPU a
        near-tie logit may round differently across program shapes).
        Works with both the contiguous and paged KV layouts; not
        combinable with prefill_buckets.
    tick_token_budget : prompt tokens each tick may spend on prefill
        chunks (default: one ``prefill_chunk``; must be >= it so every
        tick makes progress).  Requires prefill_chunk.
    spec_k : enable SPECULATIVE DECODING (serving/spec.py).  ``None``
        (default) keeps the one-token decode tick; an int k >= 1 makes
        each decode tick gather k draft tokens per slot from the
        ``proposer``, verify all k+1 window positions in ONE jitted
        dispatch (``GPTModel._compiled_spec_verify_fn`` — one compiled
        program per (k, layout), reusing the decode tick's
        ``_slot_attn``), accept the longest prefix where the target's
        argmax equals the draft plus the one bonus token, and advance
        the slot's position/KV write cursor only over the accepted
        lanes — rejected lanes leave garbage rows the next window
        rewrites before any query can see them, so rollback is a pure
        cursor reset.  Greedy outputs stay token-identical to the
        non-speculative engine (lossless greedy acceptance); seeded
        sampling also matches, because the verify window's lane j
        logits equal the one-token tick's logits for the same prefix
        and the per-request rng draws once per emitted token either
        way.  Works with both KV layouts and with chunked prefill.
        Capacity: the verify window can write up to ``spec_k`` rows
        past a request's last needed position, so ``submit`` requires
        prompt + max_new_tokens + spec_k <= max_seq_len and the paged
        admission gate reserves the extra blocks up front.
    proposer : draft-token source for speculative decoding (requires
        spec_k); defaults to ``PromptLookupProposer()`` — n-gram match
        against the slot's own prompt + emitted history, zero extra
        model.  ``DraftModelProposer(small_gpt)`` drafts with a
        smaller model sharing the tokenizer/vocab (cross-checked).
    sample_mode : where per-token sampling runs.  ``"device"`` (the
        default) FUSES sampling into the jitted decode dispatch:
        per-slot temperature/top_k/top_p ride as traced [B] lanes
        (temperature 0 = the greedy sentinel), rng keys derive on
        device from the request seed + emitted-token counter
        (``core/rng.request_key`` — a given seed reproduces across
        engine restarts), and the hot step state (current token,
        position, rng counter) stays DEVICE-RESIDENT between ticks —
        a steady-state tick uploads nothing and downloads only the
        [B] sampled ids (speculative: picks + accept counts, the
        accepted-lane count also computed on device), instead of the
        [B, V] (or [B, W, V]) logits matrix the host path pulls every
        tick.  Greedy outputs are token-identical to the host path on
        every layout; SAMPLED streams differ from host mode (device
        draws are jax categorical over fold(seed, token_index) keys,
        host draws are numpy) but are deterministic per request seed.
        ``"host"`` keeps the legacy exact numerics: logits download +
        numpy per-slot sampling (``_pick``).  Watch
        ``serving.d2h_bytes_per_tick`` / ``serving.sample_ms`` /
        ``serving.fused_sample_ticks``.
    attn_impl : which attention implementation serves the paged
        window dispatches.  ``None`` (default) inherits the model's
        ``GPTModel(attn_impl=...)`` knob (itself defaulting to
        ``"xla"``).  ``"xla"`` keeps the pure-XLA gather/scatter
        programs — one compiled executable per (layout, chunk shape,
        spec_k) window SHAPE — and remains the CPU tier-1 parity
        oracle.  ``"ragged"`` (requires the paged layout and device
        sampling) routes the decode, spec-verify, and chunked-prefill
        attention core through the Pallas RAGGED PAGED ATTENTION
        kernel (ops/ragged_paged_attn.py; interpret mode off-TPU, so
        tier-1 runs the real kernel logic): per-slot positions,
        window widths, and block tables are kernel DATA, a single
        dispatch carries one-token decode lanes, k+1 verify windows,
        and budgeted prefill chunks side by side, the
        longest-accepted-prefix scan folds into the program's
        epilogue, and the whole (chunk shape, spec_k) compile matrix
        collapses to ONE ``ragged_window`` program — watch
        ``serving.compiles_total`` and the ``decode.ragged_stream``
        trace span (plus ``serving.kv_blocks_walked_per_tick``).
        The kernel body is the flash-style ONLINE-SOFTMAX streaming
        loop: K/V are consumed one paged block at a time up to each
        lane's causal horizon, so the per-slot working set is
        O(block_size x window) — independent of context length — and
        long contexts are first-class.  Numerics: allclose to the XLA
        oracle (online softmax reorders float summation); GREEDY
        streams are token-identical to the XLA path end-to-end across
        the full layout matrix, seeded streams are deterministic
        (same seed => same stream); both asserted in
        tests/test_ragged_attn.py.  ``"ragged_gather"`` keeps the
        original materialize-the-row kernel body — O(context) working
        set, bitwise-equal to the XLA oracle on CPU, greedy AND
        seeded token-identical — as the A/B reference (trace span
        ``decode.ragged``; same dispatch path and compile-matrix
        collapse otherwise).
    mesh : TENSOR-PARALLEL SERVING over a device mesh.  ``None``
        (default) serves on one device.  An int / 1-tuple ``mp``
        degree (resolved over the first mp devices via
        ``distributed.mesh.serving_mesh``) or a prebuilt
        ``jax.sharding.Mesh`` shards the model's attention heads,
        FFN, and vocab over the mesh's 'mp' axis — the model must be
        the einsum-form tensor-parallel variant
        (``GPTModel(use_mp=True)`` or a dense checkpoint's
        ``to_tensor_parallel()`` twin), whose parameters carry the
        'mp' PartitionSpecs from distributed/sharding.py.  The
        per-layer KV pools shard over the SAME mesh on the head axis
        (each shard holds its heads' K/V of every block), block
        tables and step cursors replicate, and the fused sampling
        epilogue stays device-side on the all-gathered logits — so
        all four hot dispatch paths compile once per config with the
        sharding baked in, and the steady-state d2h contract ([B]
        ids + done bits) is unchanged.  Greedy AND seeded outputs
        are token-identical to the unsharded engine (same math
        modulo float summation order; asserted in
        tests/test_sharded_serving.py on a forced multi-device CPU
        mesh).  One sharded engine per process owns the global mesh
        (the TP activation constraints read it); unsharded sibling
        engines are unaffected.  Watch ``serving.mesh_devices`` and
        the ``shard.sync`` / ``decode.allgather`` spans.
    kv_budget_mb : size the paged pool from a PER-SHARD HBM budget
        instead of a block count: ``kv_blocks = budget //
        per_shard_block_bytes`` where one logical block costs
        ``n_layers * 2 * block_size * (H/mp) * hd * dtype`` bytes
        per shard — so the same per-chip budget holds mp x the
        blocks on a sharded engine (KV capacity scales with the
        mesh; ``serving.kv_blocks_total`` reflects the aggregate
        logical pool).  Mutually exclusive with ``kv_blocks``;
        requires the paged layout.
    async_depth : ASYNC ENGINE LOOP pipeline depth.  ``None`` (the
        default) resolves to 2 in device sample mode and 1 in host
        mode.  At depth 2 a tick DISPATCHES tick N+1's fused decode
        BEFORE consuming tick N's ids (jax async dispatch: the
        returned handles are futures; the only blocking sync is the
        consume-side ``np.asarray``, traced as ``decode.d2h_wait``),
        so admission planning and the previous tick's emit/metrics
        loop run in the gap while the device computes — on real
        hardware the inter-tick gap is pure host time, and this
        overlap is what lets kernel-side wins show up as tokens/sec.
        Blind dispatch is safe because the stop condition (EOS /
        max_new) moved ON DEVICE: per-slot eos/remaining-budget lanes
        freeze a finished row inside the dispatch, and a bit-packed
        done mask rides back with the ids, so a steady-state tick
        downloads ids + done-mask bytes and never forces an early
        sync.  The device cursor state is double-buffered: the
        in-flight tick holds the buffer it chained from while
        ``_dev_state`` tracks the newest handles; admissions /
        evictions / chunks dirty only the HOST mirrors (the next
        buffer), and a dirty event drains the pipeline before the
        mirrors are re-uploaded — recovery and parity semantics are
        unchanged, and greedy streams are token-identical to
        ``async_depth=1`` (which keeps today's synchronous tick
        bit-for-bit).  Speculative mode consumes before drafting
        (draft windows are data-dependent on the previous window's
        accepted tokens), so its overlap is limited to planning.
        Requires ``sample_mode="device"`` for depth > 1 — the host
        sampling path needs the logits on the host every tick, so
        there is no gap to overlap.  Watch ``serving.tick_overlap_ms``
        / ``serving.d2h_wait_ms`` and the ``host.overlap`` spans.
    tracing : keep a per-engine span tracer (monitor/tracing.py) fed
        by every tick: admission / prefill / chunk / decode-dispatch /
        d2h-sync / sample / emit complete-events with args (batch
        size, layout, accepted spec lanes, KV blocks in use),
        per-request lifecycle instants (queued -> admitted ->
        prefix-adopted -> first-token -> finished/evicted), and a
        compile event + ``serving.compiles_total`` bump for every new
        jitted program (layout / spec_k / chunk shape / wall time —
        the production-side compile-thrash detector).  The buffer is a
        bounded per-thread ring (``trace_capacity`` events), so the
        cost is two clock reads and a deque append per span and the
        LAST ~capacity events are always retained — the flight
        recorder.  Download it live via ``/debug/trace`` or
        ``Engine.chrome_trace()``; ``tracing=False`` swaps in a no-op
        tracer (the bench's A/B: overhead is asserted <= 5%).
    trace_capacity : per-thread ring-buffer bound, in events.
    trace_annotations : also enter a ``jax.profiler.TraceAnnotation``
        per span so engine phases land in XPlane/TensorBoard captures
        (off by default: it imports jax in the span path).
    flight_dir : directory for automatic flight-recorder dumps.  A
        failing ``step()`` snapshots the trace ring plus the in-flight
        request states into ``Engine.last_flight`` (always, in
        memory, BEFORE recovery tears the slots down) and, when
        ``flight_dir`` is set, also writes it there as a chrome-trace
        JSON (``flight_tick<N>_<pid>_<ms>.json``) for post-mortems.
    tenants : per-tenant admission policies — dict name ->
        ``TenantPolicy`` (or a plain dict of its kwargs): ``weight``
        sets the tenant's weighted-fair share of queue service within
        a priority tier (start-time fair queuing over token cost, so
        a flooding tenant cannot starve another past its weight), and
        ``rate``/``burst`` arm a token bucket charged
        ``prompt + max_new_tokens`` at submit — over-rate submits
        raise ``RateLimited`` with an honest ``retry_after``.
        Unlisted tenants get weight 1 and no rate limit.
    preemption : allow PRIORITY PREEMPTION (default True).  When the
        best queued request outranks a running one and admission is
        blocked (no free slot, or the paged gate is short on blocks),
        the lowest-priority busy slot is evicted MID-STREAM: in paged
        mode every full block of its computed history (prompt +
        emitted-so-far) goes into the prefix cache first, the request
        requeues at the head of its own lane with its emitted tokens
        preserved, and re-admission adopts the cached span so the
        resume skips re-prefill — the resumed stream is
        token-identical (greedy AND per-seed sampled: the device key
        folds the emitted-token counter, the host rng stream
        survives) to an uninterrupted run.  Victims tie-break to the
        most recently admitted (least sunk work).
    shed_deadlines : DEADLINE-AWARE LOAD SHEDDING at submit (default
        True).  Once the drain rate is measured, a request whose
        deadline (``timeout``) is already blown by the estimated
        queue wait — (in-flight remaining + queued work at its
        priority or above) / measured tokens-per-sec — is rejected
        with ``DeadlineShed`` carrying a computed ``retry_after``
        instead of burning slot time on a result nobody will read.
    faults : a ``serving.faults.FaultInjector`` — deterministic,
        seeded failure points (dispatch raise, d2h hang, pool
        exhaustion, slow host tick, proposer failure) threaded
        through the tick for chaos testing; None (default) disables
        every site at zero cost.
    watchdog_s : arm a ``TickWatchdog``: a tick exceeding this many
        seconds (wedged dispatch / hung d2h) is flight-recorded
        immediately and marked, so cooperative blocking points raise
        ``WatchdogTimeout`` into the normal step-failure recovery
        instead of hanging the engine forever.

    ``step()`` is single-threaded by design — run it from one loop
    (``run_until_idle`` or the ``start()`` background thread).
    ``submit()`` is thread-safe and may be called from anywhere
    (e.g. HTTP handler threads).
    """

    def __init__(self, model, num_slots=4, max_seq_len=None,
                 max_queue=0, registry=None, prefill_buckets=None,
                 kv_block_size=None, kv_blocks=None, prefix_cache=True,
                 prefill_chunk=None, tick_token_budget=None,
                 spec_k=None, proposer=None, sample_mode="device",
                 attn_impl=None, mesh=None, kv_budget_mb=None,
                 async_depth=None, tracing=True,
                 trace_capacity=16384, trace_annotations=False,
                 flight_dir=None, tenants=None, preemption=True,
                 shed_deadlines=True, faults=None, watchdog_s=None,
                 weight_dtype=None, kv_dtype=None, adapters=None,
                 max_adapters=None, max_lora_rank=None,
                 kv_host_mb=None):
        if getattr(model, "scan_layers", False):
            model = model._sync_decode_twin()
        model.eval()
        self.model = model
        # -- quantized serving (serving/quant.py) ----------------------
        # weight relayout runs HERE, before the KV-dtype resolution and
        # the parameter/buffer snapshots below, so the int8 codes +
        # scales are registered buffers that ride b_list into every
        # compiled hot path, and kv pools stay in the projection's
        # declared compute dtype
        self._weight_quant = weight_dtype is not None
        if self._weight_quant:
            if str(weight_dtype) != "int8":
                raise ValueError(
                    f"weight_dtype must be 'int8' (or None to serve "
                    f"the checkpoint's own dtype), got {weight_dtype!r}")
            if getattr(model.blocks[0].attn, "use_mp", False):
                raise ValueError(
                    "weight_dtype='int8' cannot relayout the tensor-"
                    "parallel einsum form (use_mp=True): its fused "
                    "qkv/ffn weights are not nn.Linear layers — "
                    "quantize the dense checkpoint before "
                    "to_tensor_parallel(), or serve it dense")
            from .quant import relayout_weights_int8
            relayout_weights_int8(model)
        self._kv_quant = kv_dtype is not None
        if self._kv_quant and str(kv_dtype) != "int8":
            raise ValueError(
                f"kv_dtype must be 'int8' (or None for the compute "
                f"dtype), got {kv_dtype!r}")
        max_position = \
            model.embeddings.position_embeddings.weight.shape[0]
        self.max_seq_len = int(max_seq_len or max_position)
        if self.max_seq_len > max_position:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} exceeds the model's "
                f"position table ({max_position})")
        self.num_slots = int(num_slots)
        try:  # the HTTP edge validates token ids against this
            self.vocab_size = int(
                model.embeddings.word_embeddings.weight.shape[0])
        except AttributeError:
            self.vocab_size = None
        # -- overload protection: tenants, priorities, shedding ---------
        self._tenant_policies = {}
        self._buckets = {}
        for name, pol in (tenants or {}).items():
            if isinstance(pol, dict):
                pol = TenantPolicy(**pol)
            elif not isinstance(pol, TenantPolicy):
                raise ValueError(
                    f"tenants[{name!r}] must be a TenantPolicy or a "
                    f"dict of its kwargs, got {type(pol).__name__}")
            self._tenant_policies[str(name)] = pol
            if pol.rate is not None:
                self._buckets[str(name)] = TokenBucket(pol.rate,
                                                       pol.burst)
        self.queue = RequestQueue(
            max_queue=max_queue,
            weights={n: p.weight
                     for n, p in self._tenant_policies.items()})
        self.scheduler = Scheduler(self.num_slots, self.queue)
        self._preemption = bool(preemption)
        self._shed_deadlines = bool(shed_deadlines)
        self._preempt_log = deque(maxlen=64)  # recent preemptions —
        #   rides in flight-recorder dumps so a post-mortem shows WHY
        #   a slot was evicted
        self._gate_declined = False  # the paged admission gate turned
        #   the queue head away this tick (short on blocks) — the
        #   preemption probe's KV-pressure signal
        self._draining = False       # stop(drain=True) in progress:
        #   no new submits, no new admissions; in-flight slots finish
        self._rate_win = deque(maxlen=64)  # (t, emitted) per emitting
        #   tick — the measured drain rate behind Retry-After and
        #   deadline shedding
        self._ovl_lock = threading.Lock()  # guards _rate_win and
        #   _preempt_log: the engine thread appends while handler /
        #   watchdog threads snapshot (an unguarded deque raises
        #   "mutated during iteration" mid-read)
        self.faults = faults
        self.watchdog_s = (None if watchdog_s is None
                           else float(watchdog_s))
        self._watchdog = None
        self._watchdog_fired = False
        self._tick_started_at = None  # watchdog heartbeat: set at
        #   tick entry, cleared at exit

        import jax.numpy as jnp
        attn0 = model.blocks[0].attn
        self._nh, self._hd = attn0.num_heads, attn0.head_dim
        if attn0.use_mp:
            kv_dtype = attn0.qkv_weight._data.dtype
        else:
            # compute_dtype first: a weight-only-int8 projection's
            # .weight property would materialize the dequantized matrix
            kv_dtype = getattr(attn0.qkv_proj, "compute_dtype", None) \
                or attn0.qkv_proj.weight._data.dtype
        self._kv_dtype = kv_dtype
        # the dtype LABEL for compiled-program cache keys, /healthz,
        # and the migration wire: a quantized pool keeps _kv_dtype as
        # its f32 COMPUTE dtype (attention math, scratch views) but
        # must never share programs or migrate blocks with an fp
        # engine of the same compute dtype
        self._kv_dtype_str = "int8" if self._kv_quant \
            else str(self._kv_dtype)
        self._weight_dtype_str = "int8" if self._weight_quant \
            else str(self._kv_dtype)
        # -- 2-D (mp, dp) serving mesh (mesh=...) ----------------------
        # ``mesh`` accepts an int mp degree, an (mp,) or (mp, dp)
        # tuple (resolved via distributed.mesh.serving_mesh over the
        # first mp*dp devices), or a prebuilt jax Mesh.  With mp > 1
        # the model must be the einsum-form tensor-parallel variant
        # (GPTModel(use_mp=True), or a dense checkpoint's
        # ``to_tensor_parallel()`` twin): its parameters carry 'mp'
        # PartitionSpecs, and placing params + KV pools sharded makes
        # every existing jitted dispatch compile ONCE PER CONFIG with
        # the sharding baked into the program — GSPMD splits attention
        # heads / FFN / vocab and inserts the psum/all-gather
        # collectives.  With dp > 1 the BATCH shards: each dp shard
        # owns num_slots/dp slot rows of every [B]-leading cursor
        # array, the block tables, and a contiguous range of the KV
        # block pool rows (params replicate over 'dp'), so one
        # compiled program spans both axes — dp multiplies concurrent
        # slots the way mp multiplies per-block capacity.  The
        # host-side tick protocol (host mirrors, [B]-id downloads,
        # the 17 B steady-state d2h) is unchanged.
        self.mesh = None
        self.mp = 1
        self.dp = 1
        self.mesh_axes = None
        self._repl_sharding = None
        self._kv_sharding = None
        self._kv_scale_sharding = None
        self._state_sharding = None
        self._table_sharding = None
        self._kv_block_bytes_per_shard = None
        self._kv_code_bytes_per_shard = None
        self._kv_scale_bytes_per_shard = None
        if mesh is not None:
            import jax
            from jax.sharding import (Mesh, NamedSharding,
                                      PartitionSpec)
            from ..distributed import mesh as mesh_mod
            if isinstance(mesh, (int, np.integer)):
                mesh = mesh_mod.serving_mesh(int(mesh))
            elif isinstance(mesh, (tuple, list)):
                if len(mesh) not in (1, 2):
                    raise ValueError(
                        f"mesh shape must be (mp,) or (mp, dp), got "
                        f"{tuple(mesh)} — the serving engine shards "
                        "over a tensor-parallel and a data-parallel "
                        "axis")
                mesh = mesh_mod.serving_mesh(
                    int(mesh[0]),
                    int(mesh[1]) if len(mesh) == 2 else 1)
            elif not isinstance(mesh, Mesh):
                raise ValueError(
                    f"mesh must be an int mp degree, an (mp,) / "
                    f"(mp, dp) tuple, or a jax Mesh, got "
                    f"{type(mesh).__name__}")
            self.mesh = mesh
            self.mp = int(mesh.shape.get("mp", 1))
            self.dp = int(mesh.shape.get("dp", 1))
            extra = {k: int(v) for k, v in mesh.shape.items()
                     if k not in ("mp", "dp") and int(v) > 1}
            if extra:
                # a pp/sp/... axis would silently REPLICATE params and
                # KV pools across it (the serving specs only name
                # 'mp' and 'dp') — not a silent HBM tax
                raise ValueError(
                    f"serving mesh must shard only the 'mp' and 'dp' "
                    f"axes; got extra axes {extra} — build one with "
                    "distributed.mesh.serving_mesh(mp, dp)")
            self.mesh_axes = ({k: int(v) for k, v in mesh.shape.items()
                               if int(v) > 1} or {"mp": 1})
            if self.mp > 1:
                if not attn0.use_mp:
                    raise ValueError(
                        "mesh with mp > 1 requires the tensor-parallel"
                        " model form: build with GPTModel(use_mp=True)"
                        " or convert a dense checkpoint with "
                        "model.to_tensor_parallel() — the dense fused "
                        "qkv layout cannot shard its head axis (see "
                        "distributed/sharding.py)")
                if self._nh % self.mp:
                    raise ValueError(
                        f"num_heads ({self._nh}) must divide by the "
                        f"mesh's mp degree ({self.mp}) — attention "
                        "shards whole heads")
            if self.dp > 1 and self.num_slots % self.dp:
                raise ValueError(
                    f"num_slots ({self.num_slots}) must divide by the "
                    f"mesh's dp degree ({self.dp}) — each dp shard "
                    "owns an equal contiguous range of batch slots")
            if self.mp * self.dp > 1:
                # the TP layers' activation sharding constraints
                # (distributed/sharding.py _constraint) read the
                # process-global mesh, and the shard_map-wrapped
                # ragged kernel discovers its mesh the same way; one
                # sharded engine per process owns it (sibling
                # UNSHARDED engines are unaffected — dense models
                # carry no constraints and the unsharded kernel path
                # never consults the mesh)
                mesh_mod.set_mesh(mesh)
            # the canonical serving layout table lives in
            # distributed/sharding.py (SERVING_SPECS) so the engine,
            # the shard_map-wrapped ragged kernel, and the tests
            # agree on one source of truth; specs name 'dp' even at
            # dp == 1 (a size-1 axis), so the program shape is
            # uniform across layouts
            from ..distributed.sharding import serving_sharding
            self._repl_sharding = serving_sharding(mesh, "replicated")
            self._kv_sharding = serving_sharding(mesh, "kv")
            self._kv_scale_sharding = serving_sharding(mesh,
                                                       "kv_scale")
            self._state_sharding = serving_sharding(mesh, "state")
            self._table_sharding = serving_sharding(mesh, "table")
            # place params per their TP PartitionSpecs (replicated
            # when none — and always replicated over 'dp'): every
            # compiled dispatch then sees sharded weight inputs and
            # GSPMD partitions the program
            for _, p in model.named_parameters():
                spec = getattr(p, "partition_spec", None)
                sh = (NamedSharding(mesh, spec) if spec is not None
                      else self._repl_sharding)
                p._data = jax.device_put(p._data, sh)
            for _, b in model.named_buffers():
                b._data = jax.device_put(b._data, self._repl_sharding)
        self._kv_budget_mb = (None if kv_budget_mb is None
                              else float(kv_budget_mb))
        if prefill_buckets == "pow2":
            bs, b = [], 8
            while b < self.max_seq_len:
                bs.append(b)
                b *= 2
            bs.append(self.max_seq_len)
            self._prefill_buckets = bs
        elif prefill_buckets:
            bs = sorted({int(x) for x in prefill_buckets})
            if bs[0] < 1 or bs[-1] > self.max_seq_len:
                raise ValueError(
                    f"prefill_buckets must lie in [1, {self.max_seq_len}]"
                    f", got {bs}")
            if bs[-1] < self.max_seq_len:
                bs.append(self.max_seq_len)  # every legal prompt fits
            self._prefill_buckets = bs
        else:
            self._prefill_buckets = None
        self._chunk = None
        self._tick_budget = None
        if prefill_chunk is not None:
            c = int(prefill_chunk)
            if c < 1 or self.max_seq_len % c:
                raise ValueError(
                    f"prefill_chunk must be >= 1 and divide max_seq_len"
                    f" ({self.max_seq_len}), got {c} — dividing keeps "
                    "the chunk window from clamping onto live cache "
                    "rows")
            if self._prefill_buckets is not None:
                raise ValueError(
                    "prefill_chunk cannot combine with prefill_buckets:"
                    " the fixed chunk shape already bounds prefill "
                    "compiles")
            b = int(tick_token_budget) if tick_token_budget is not None \
                else c
            if b < c:
                raise ValueError(
                    f"tick_token_budget ({b}) must cover at least one "
                    f"prefill_chunk ({c}), or no tick could ever make "
                    "prefill progress")
            self._chunk = c
            self._tick_budget = b
        elif tick_token_budget is not None:
            raise ValueError(
                "tick_token_budget requires prefill_chunk (it bounds "
                "the chunked-prefill spend per tick)")
        self._spec_k = None
        self.proposer = None
        if spec_k is not None:
            k = int(spec_k)
            if k < 1:
                raise ValueError(f"spec_k must be >= 1, got {k}")
            if k + 2 > self.max_seq_len:
                raise ValueError(
                    f"spec_k={k} leaves no room for any request in a "
                    f"{self.max_seq_len}-position slot (the verify "
                    "window needs prompt + max_new_tokens + spec_k to "
                    "fit)")
            self._spec_k = k
            if proposer is None:
                from .spec import PromptLookupProposer
                proposer = PromptLookupProposer()
            pv = getattr(proposer, "vocab_size", None)
            if pv is not None and self.vocab_size is not None \
                    and int(pv) != self.vocab_size:
                raise ValueError(
                    f"proposer vocab ({pv}) != target model vocab "
                    f"({self.vocab_size}) — a draft from a different "
                    "tokenizer can never match and only burns the "
                    "verify window")
            self.proposer = proposer
        elif proposer is not None:
            raise ValueError(
                "proposer requires spec_k (the draft window width "
                "fixes the compiled verify program's shape)")
        if sample_mode not in ("device", "host"):
            raise ValueError(
                f"sample_mode must be 'device' or 'host', got "
                f"{sample_mode!r}")
        self.sample_mode = sample_mode
        if async_depth is None:
            async_depth = 2 if sample_mode == "device" else 1
        async_depth = int(async_depth)
        if async_depth < 1:
            raise ValueError(
                f"async_depth must be >= 1, got {async_depth}")
        if async_depth > 1 and sample_mode != "device":
            raise ValueError(
                "async_depth > 1 requires sample_mode='device': the "
                "host sampling path downloads the logits and samples "
                "on the host every tick, so there is no device-compute "
                "gap to overlap")
        self.async_depth = async_depth
        self._paged = kv_block_size is not None
        if self._kv_quant:
            if not self._paged:
                raise ValueError(
                    "kv_dtype='int8' requires the paged KV layout "
                    "(kv_block_size=...): quantization is per-block — "
                    "the contiguous pools have no block granularity "
                    "to hang a scale on")
            if sample_mode != "device":
                raise ValueError(
                    "kv_dtype='int8' requires sample_mode='device': "
                    "the host sampling paths dispatch the per-layer "
                    "fp decode programs, which have no dequantizing "
                    "gather — only the fused device-sampling "
                    "dispatches thread QuantKV pools")
        if self._paged:
            bsz = int(kv_block_size)
            if bsz < 1 or self.max_seq_len % bsz:
                raise ValueError(
                    f"kv_block_size must be >= 1 and divide max_seq_len"
                    f" ({self.max_seq_len}), got {bsz}")
            if self._prefill_buckets is not None:
                raise ValueError(
                    "prefill_buckets cannot combine with kv_block_size:"
                    " the paged prefill compiles per (context, tail) "
                    "length instead of per bucket")
            self._bs = bsz
            self._bps = self.max_seq_len // bsz  # blocks per full slot
            # per-shard footprint of ONE logical block: each mesh
            # shard stores only its H/mp heads' K/V rows, so a fixed
            # per-chip HBM budget (kv_budget_mb) buys mp x the blocks
            # — sharding the model scales KV capacity, not just
            # weights (kvcache.per_shard_block_bytes)
            # quantized pools store int8 codes plus the parallel f32
            # scale pool; both count against the budget so capacity
            # accounting adds up (code + scale components exposed as
            # serving.kv_block_bytes / serving.kv_scale_bytes)
            store_dtype = "int8" if self._kv_quant else self._kv_dtype
            self._kv_code_bytes_per_shard = per_shard_block_bytes(
                bsz, self._nh, self._hd, store_dtype,
                len(model.blocks), self.mp)
            self._kv_block_bytes_per_shard = per_shard_block_bytes(
                bsz, self._nh, self._hd, store_dtype,
                len(model.blocks), self.mp,
                scale_dtype="float32" if self._kv_quant else None)
            self._kv_scale_bytes_per_shard = (
                self._kv_block_bytes_per_shard
                - self._kv_code_bytes_per_shard)
            if kv_budget_mb is not None:
                if kv_blocks is not None:
                    raise ValueError(
                        "kv_budget_mb and kv_blocks are two answers to"
                        " one question (pool size) — pass one")
                # per-chip budget -> per-dp-shard block count; every
                # dp shard owns its own pool range, so the managed
                # total scales dp x on top of the mp x that the
                # smaller per-shard block bytes already buy: capacity
                # scales mp*dp at a fixed per-chip HBM budget
                managed = self.dp * int(
                    self._kv_budget_mb * 2 ** 20
                    // self._kv_block_bytes_per_shard)
            else:
                managed = (self.num_slots * self._bps
                           if kv_blocks is None else int(kv_blocks))
                # the dp shard ranges must be equal; round an explicit
                # kv_blocks UP so capacity is never silently reduced
                managed += -managed % self.dp
            if managed // self.dp < self._bps:
                # blame the knob the caller actually turned
                src = (f"kv_budget_mb={self._kv_budget_mb:g} "
                       f"(-> {managed // self.dp} blocks at "
                       f"{self._kv_block_bytes_per_shard} B/block/"
                       "shard)" if kv_budget_mb is not None
                       else f"kv_blocks={managed}"
                       + (f" (/{self.dp} dp shards)"
                          if self.dp > 1 else ""))
                raise ValueError(
                    f"{src} cannot hold even one max-length request "
                    f"({self._bps} blocks"
                    + (" per dp shard)" if self.dp > 1 else ")"))
            self._kv_managed = managed
            self._prefix_enabled = bool(prefix_cache)
        elif kv_budget_mb is not None:
            raise ValueError(
                "kv_budget_mb requires the paged KV layout "
                "(kv_block_size=...): the contiguous pools are sized "
                "by num_slots * max_seq_len, not by a block budget")
        # -- host-RAM offload tier (serving/offload.py) -----------------
        # A second, much larger home for KV blocks the device pool
        # evicts: demotes ride the prefix trie's evict hook (async
        # gather, materialized at tick boundaries), promotes ride the
        # admission gate's prefix match (host hit -> import into fresh
        # blocks, seed the trie, skip prefill for the restored span).
        self.host_store = None
        if kv_host_mb is not None:
            if not self._paged:
                raise ValueError(
                    "kv_host_mb requires the paged KV layout "
                    "(kv_block_size=...): the host tier parks whole "
                    "blocks — the contiguous pools have none")
            if not self._prefix_enabled:
                raise ValueError(
                    "kv_host_mb requires prefix_cache=True: demotes "
                    "are fed by the trie's eviction and promotes by "
                    "admission's prefix match")
            from .offload import HostBlockStore
            self.host_store = HostBlockStore(
                kv_host_mb, self._bs, self._nh, self._hd,
                len(list(model.blocks)), self._kv_dtype_str)
        # -- ragged paged attention (attn_impl="ragged") ----------------
        if attn_impl is None:
            attn_impl = getattr(model, "attn_impl", "xla")
        if attn_impl not in ("xla", "ragged", "ragged_gather"):
            raise ValueError(
                f"attn_impl must be 'xla', 'ragged' or "
                f"'ragged_gather', got {attn_impl!r}")
        if attn_impl in ("ragged", "ragged_gather"):
            if not self._paged:
                raise ValueError(
                    f"attn_impl={attn_impl!r} requires the paged KV "
                    "layout (kv_block_size=...): the kernel reads K/V "
                    "through per-slot block tables — the contiguous "
                    "layout keeps the XLA path")
            if sample_mode != "device":
                raise ValueError(
                    f"attn_impl={attn_impl!r} requires "
                    "sample_mode='device': sampling, the acceptance "
                    "scan, and the stop condition all run in the "
                    "ragged program's epilogue")
        self.attn_impl = attn_impl
        # both ragged kernels share the dispatch path; "ragged" is
        # the streaming (online-softmax) body, "ragged_gather" the
        # materialize-the-row A/B reference (ops/ragged_paged_attn.py)
        self._ragged = attn_impl in ("ragged", "ragged_gather")
        # the ONE ragged program's static window: wide enough for a
        # one-token decode lane, the k+1 spec-verify window, and a
        # prefill chunk — per-slot width is runtime data, so the
        # engine compiles exactly one paged window program however
        # traffic mixes (the compile-matrix collapse)
        self._wmax = max(1, (self._spec_k + 1) if self._spec_k else 1,
                         self._chunk or 1)
        self._ragged_fn = None  # resolved jitted ragged-window handle
        self._zero_scale_fn = None  # jitted fresh-block scale zeroer
        #   (kv_dtype='int8'; compiled once per config — see
        #   _zero_fresh_scales)
        # -- multi-adapter (LoRA) lanes (serving/lora.py) ---------------
        # "which adapter" is per-slot DATA gathered from fixed-shape
        # banks inside the traced programs, so every adapter — loaded
        # now or hot-loaded later — shares the engine's one compiled
        # program per config.
        self.adapters = None
        if adapters is not None or max_adapters is not None:
            if sample_mode != "device":
                raise ValueError(
                    "adapters require sample_mode='device': the host "
                    "sampling paths dispatch per-layer programs that "
                    "do not thread the per-slot LoRA lanes")
            if self.mesh is not None or attn0.use_mp:
                raise ValueError(
                    "adapters cannot combine with tensor-parallel "
                    "serving (mesh=... / use_mp models): the LoRA "
                    "delta rides the dense out_proj form")
            init = dict(adapters or {})
            for _nm, _ad in init.items():
                if not isinstance(_ad, LoRAAdapter):
                    raise TypeError(
                        f"adapters[{_nm!r}] must be a LoRAAdapter, "
                        f"got {type(_ad).__name__}")
            n_ad = (int(max_adapters) if max_adapters is not None
                    else max(len(init), 1))
            if n_ad < len(init):
                raise ValueError(
                    f"max_adapters={n_ad} cannot hold the "
                    f"{len(init)} adapters passed at construction")
            r_max = (int(max_lora_rank) if max_lora_rank is not None
                     else max([a.rank for a in init.values()] or [8]))
            hidden = int(
                model.embeddings.word_embeddings.weight.shape[1])
            self.adapters = AdapterRegistry(
                len(list(model.blocks)), hidden, n_ad, r_max)
            for _nm in sorted(init):
                self.adapters.load(_nm, init[_nm])
        # -- tracing / flight recorder ---------------------------------
        self.tracer = (monitor.Tracer(capacity=trace_capacity,
                                      annotate=trace_annotations)
                       if tracing else monitor.NullTracer())
        self._flight_dir = flight_dir
        self.last_flight = None        # chrome-trace dict of the most
        self.last_flight_path = None   # recent step failure (+ file)
        self.tick_no = 0
        self._reset_pools()
        self._rngs = {}  # request id -> np.random.Generator (sampling)

        params = dict(model.named_parameters())
        self._params = params
        self._pnames = sorted(params)
        self._bnames_all = tuple(sorted(dict(model.named_buffers())))

        # -- metrics -----------------------------------------------------
        reg = registry or monitor.default_registry()
        self.registry = reg
        self._m_queue = reg.gauge(
            "serving.queue_depth", "requests waiting for a slot")
        self._m_occ = reg.gauge(
            "serving.slot_occupancy", "busy slots out of num_slots")
        self._m_slots = reg.gauge(
            "serving.slot_total", "configured slot pool size")
        self._m_slots.set(self.num_slots)
        self._m_mesh = reg.gauge(
            "serving.mesh_devices", "devices in this engine's serving "
            "mesh (mp x dp shards; 1 = unsharded single device)")
        self._m_mesh.set(self.mesh.size if self.mesh is not None else 1)
        self._m_tokens = reg.counter(
            "serving.tokens_total", "generated tokens")
        self._m_reqs = reg.counter(
            "serving.requests_total", "submitted requests")
        self._m_done = reg.counter(
            "serving.requests_completed", "finished requests")
        self._m_timeout = reg.counter(
            "serving.requests_timeout", "requests expired in queue")
        self._m_ttft = reg.histogram(
            "serving.ttft_ms", "time to first token (ms)")
        self._m_tpot = reg.histogram(
            "serving.tpot_ms", "time per output token after the first "
            "(ms, per finished request)")
        self._m_rate = monitor.RateMeter(reg.gauge(
            "serving.tokens_per_sec", "windowed decode throughput"))
        # paged-KV surface (registered always so dashboards see the
        # names; they stay zero in contiguous mode)
        self._m_prefill_tokens = reg.counter(
            "serving.prefill_tokens", "prompt tokens actually computed"
            " in prefill (prefix-cache hits skip the shared span)")
        self._m_kv_blocks = reg.gauge(
            "serving.kv_blocks_in_use", "paged KV blocks referenced by"
            " slots or cached prefixes")
        self._m_kv_total = reg.gauge(
            "serving.kv_blocks_total", "paged KV pool size in blocks")
        self._m_kv_block_bytes = reg.gauge(
            "serving.kv_block_bytes", "per-shard K/V ROW bytes of one "
            "logical block across all layers (int8 code bytes when "
            "kv_dtype='int8')")
        self._m_kv_scale_bytes = reg.gauge(
            "serving.kv_scale_bytes", "per-shard scale-pool bytes of "
            "one logical block (0 unless kv_dtype='int8') — "
            "kv_blocks_total * (kv_block_bytes + kv_scale_bytes) "
            "<= kv_budget_mb")
        if self._paged:
            self._m_kv_total.set(self._kv_managed)
            self._m_kv_block_bytes.set(self._kv_code_bytes_per_shard)
            self._m_kv_scale_bytes.set(self._kv_scale_bytes_per_shard)
        self._m_prefix_hits = reg.counter(
            "serving.prefix_hits", "admissions that adopted a cached "
            "prompt prefix")
        self._m_prefix_hit_tokens = reg.counter(
            "serving.prefix_hit_tokens", "prompt tokens served from "
            "cached prefix blocks (prefill skipped)")
        self._m_prefix_evictions = reg.counter(
            "serving.prefix_evictions", "cached prefix blocks evicted "
            "(LRU) under pool pressure")
        # chunked-prefill surface (registered always; zero when
        # prefill_chunk is off)
        self._m_chunks = reg.counter(
            "serving.prefill_chunks", "chunked-prefill dispatches")
        self._m_stall = reg.histogram(
            "serving.decode_stall_ms", "gap between consecutive decode "
            "dispatches while slots were decoding — the time decoders "
            "stalled on interleaved prefill work (ms)")
        self._m_decode_batch = reg.gauge(
            "serving.decode_batch", "DECODING slots in the latest "
            "decode dispatch")
        # speculative-decoding surface (registered always; zero when
        # spec_k is off)
        self._m_spec_proposed = reg.counter(
            "serving.spec_proposed", "draft lanes proposed to the "
            "speculative verify dispatch")
        self._m_spec_accepted = reg.counter(
            "serving.spec_accepted", "draft lanes accepted (their "
            "token emitted from a matched lane)")
        self._m_spec_windows = reg.counter(
            "serving.spec_windows", "per-slot verify windows scored "
            "(one speculative dispatch covers every DECODING slot; "
            "a request's final window may propose fewer than spec_k "
            "lanes, so accepted/windows is the honest mean-accepted-"
            "lanes denominator)")
        self._m_spec_rate = reg.gauge(
            "serving.spec_acceptance_rate", "accepted / proposed "
            "draft lanes, cumulative over this engine's lifetime")
        self._m_spec_tpt = reg.gauge(
            "serving.spec_tokens_per_tick", "tokens emitted per "
            "DECODING slot by the latest speculative verify dispatch "
            "(1.0 = nothing accepted, spec_k+1 = full window)")
        # sampling-mode surface (registered always; sample_ms stays
        # empty in device mode, fused_sample_ticks zero in host mode)
        self._m_d2h = reg.gauge(
            "serving.d2h_bytes_per_tick", "bytes the latest decode "
            "dispatch downloaded to the host (host mode pulls the "
            "[B, V] logits — [B, W, V] speculative; device mode only "
            "the sampled ids + accept counts)")
        self._m_sample_ms = reg.histogram(
            "serving.sample_ms", "host-side per-tick sampling + emit "
            "loop (ms; host sample_mode only — device mode samples "
            "inside the dispatch)")
        self._m_fused_ticks = reg.counter(
            "serving.fused_sample_ticks", "decode dispatches that "
            "sampled on device (sample_mode='device')")
        self._m_kv_blocks_walked = reg.gauge(
            "serving.kv_blocks_walked_per_tick", "KV blocks the "
            "ragged kernel walked in the latest dispatch, summed over "
            "lanes: the streaming kernel (attn_impl='ragged') stops "
            "at each lane's causal horizon ceil((pos + width) / "
            "block_size), so this tracks LIVE context; the gather "
            "variant (attn_impl='ragged_gather') always concatenates "
            "the full per-slot table")
        # max context length any request has reached on this engine
        # (slot cursor high-water: prefilled prompt + decoded tokens)
        # — surfaced in /healthz and /debug/requests so the fleet's
        # long-context exposure is observable per replica
        self._max_context_len = 0
        # async-loop surface (registered always; overlap stays empty
        # and async_depth reads 1 when the loop is synchronous)
        self._m_async_depth = reg.gauge(
            "serving.async_depth", "engine pipeline depth (1 = "
            "synchronous tick, 2 = tick N+1 dispatched before tick N "
            "is consumed)")
        self._m_async_depth.set(self.async_depth)
        self._m_overlap = reg.histogram(
            "serving.tick_overlap_ms", "host work (admission planning "
            "+ previous tick's emit/metrics) done per tick WHILE a "
            "decode dispatch was in flight — the scheduling time the "
            "async loop hides behind device compute (ms)")
        self._m_d2h_wait = reg.histogram(
            "serving.d2h_wait_ms", "blocking wait materializing a "
            "dispatched tick's ids + done mask (ms) — the only sync "
            "point of the async loop; near-zero means the host fully "
            "hid its work behind device compute")
        # compile-event surface: every NEW jitted program of this
        # engine's model (any trigger — this engine, a sibling engine,
        # generate()) bumps the counter and lands in the trace; a
        # steady-state increase is the compile-thrash signal the
        # bounded chunk/spec/bucket shapes exist to prevent
        self._m_compiles = reg.counter(
            "serving.compiles_total", "new jitted programs compiled "
            "since engine start (first-call trace + XLA compile "
            "events; nonzero growth in steady state = the program "
            "cache is thrashing)")
        self._m_compile_ms = reg.histogram(
            "serving.compile_ms", "wall time of each new program's "
            "first call (jax trace + XLA compile + first run, ms)")
        # overload-protection surface: preemption / shedding /
        # fairness / chaos (registered always; zero when idle)
        self._m_preempt = reg.counter(
            "serving.preemptions_total", "mid-stream preemptions: a "
            "running lower-priority request evicted back to the "
            "queue (emitted tokens preserved; paged blocks returned "
            "to the prefix cache)")
        self._m_resumed = reg.counter(
            "serving.resumed_total", "re-admissions of previously "
            "preempted requests (prefix adoption skips the shared "
            "span's re-prefill in paged mode)")
        self._m_shed_deadline = reg.counter(
            "serving.shed_deadline_total", "requests rejected at "
            "submit because the estimated queue drain already blew "
            "their deadline (DeadlineShed, honest Retry-After)")
        self._m_shed_rate = reg.counter(
            "serving.shed_rate_limited_total", "requests rejected at "
            "submit by a tenant token bucket (RateLimited)")
        self._m_shed_queue = reg.counter(
            "serving.shed_queue_full_total", "requests rejected at "
            "submit because the admission queue was at max_queue")
        self._m_drain_tps = reg.gauge(
            "serving.drain_rate_tps", "measured decode drain rate "
            "(tokens/sec over the recent emitting-tick window) — the "
            "denominator of Retry-After and deadline-shed estimates")
        self._m_watchdog = reg.counter(
            "serving.watchdog_fires", "ticks the watchdog declared "
            "wedged (flight-recorded; cooperative blocks raise into "
            "step recovery)")
        self._m_faults = reg.counter(
            "serving.faults_injected", "fault-injection sites fired "
            "(serving/faults.py — nonzero only under a chaos "
            "harness)")
        self._m_proposer_failures = reg.counter(
            "serving.proposer_failures", "proposer calls that raised "
            "— degraded to an empty draft window (verify emits the "
            "bonus token) instead of failing the tick")
        self._m_kv_migrated = reg.counter(
            "serving.kv_blocks_migrated", "paged KV blocks exported "
            "toward another replica (stream migration + prefix "
            "warming; counted on the EXPORT side only, so a shared "
            "registry never double-counts a transfer)")
        self._m_kv_host_blocks = reg.gauge(
            "serving.kv_host_blocks", "KV blocks resident in the "
            "host-RAM offload tier (kv_host_mb=...)")
        self._m_kv_host_bytes = reg.gauge(
            "serving.kv_host_bytes", "bytes the host-RAM offload tier "
            "holds (codes + scales for int8 pools)")
        self._m_offload_demotes = reg.counter(
            "serving.offload_demotes", "KV blocks demoted device -> "
            "host at eviction (materialized at tick boundaries)")
        self._m_offload_promotes = reg.counter(
            "serving.offload_promotes", "KV blocks promoted host -> "
            "device at admission (restored instead of recomputed)")
        self._m_offload_hit_tokens = reg.counter(
            "serving.offload_hit_tokens", "prompt tokens whose "
            "prefill was skipped via a host-tier restore (the "
            "host-side share of prefix_hit_tokens)")
        # weakref'd listener: a collected engine returns False from the
        # callback and the model drops it — engines must not leak into
        # the model's listener list across their lifetimes
        wm = weakref.WeakMethod(self._on_compile)

        def _compile_cb(kind, key, wall_s, _wm=wm):
            bound = _wm()
            if bound is None:
                return False
            bound(kind, key, wall_s)
            return True

        self._compile_cb = _compile_cb
        self._compile_cb_active = False
        self._register_compile_listener()

        self._last_decode_end = None  # stall anchor: end of the last
        #   decode dispatch, cleared when no slot is decoding
        self._evicted_in_tick = 0     # monotonic eviction counter; the
        #   tick reads DELTAS to keep the occupancy gauge exact without
        #   re-locking the scheduler after the decode dispatch
        self._insert_fn = None
        self._tick_fn = None    # resolved jitted slot-decode handle
        self._spec_fn = None    # resolved jitted spec-verify handle
        self._fused_fn = None   # resolved fused decode+sample handle
        self._fused_spec_fn = None  # fused verify+sample/accept handle
        self._p_arrays = None   # lazy snapshots of param/buffer handles
        self._b_arrays = None   # (see refresh_params)
        self._thread = None
        self._stop = threading.Event()
        self._wake = threading.Event()  # event-driven loop wake:
        #   submit() sets it, so an idle engine blocks instead of
        #   polling and admission latency stops paying poll jitter
        self._mig_lock = threading.Lock()
        self._migrate_demands = []  # _MigrateDemand orders, registered
        #   by any thread (migrate_out / migrate_in / export_prefix /
        #   import_prefix) and serviced by the engine thread at the
        #   next tick boundary
        self._migration_log = deque(maxlen=64)  # {"tick","dir",...}
        self._overlap_acc = 0.0  # per-tick overlapped-host-work clock
        self._drain_on_exit = None  # set to a loop's stop event when
        #                             that loop must drain on exit

    def _alloc_pool(self, shape):
        """One per-layer K/V pool, mesh-sharded on the head axis when
        the engine serves tensor-parallel: each shard materializes
        only its H/mp heads' slice (axis 2 in both layouts), so pool
        HBM per chip shrinks by mp — the headroom kv_budget_mb turns
        into extra logical blocks.  Sharded pools are allocated by a
        COMPILED zeros program with the sharding as its output spec,
        so each device materializes only its own shard — a whole-pool
        array staged through one device would defeat the very
        capacity scaling, since an aggregate pool sized for the mesh
        need not fit any single chip.  (Not
        make_array_from_callback: its per-shard host callback
        segfaults intermittently under this jax version.)"""
        import jax.numpy as jnp
        if self._kv_sharding is None:
            if self._kv_quant:
                from .quant import QuantKV
                return QuantKV(
                    jnp.zeros(shape, jnp.int8),
                    jnp.zeros((shape[0], shape[2]), jnp.float32))
            return jnp.zeros(shape, self._kv_dtype)
        import jax
        fn = getattr(self, "_pool_zeros_fn", None)
        if fn is None:
            shape = tuple(shape)
            dtype = self._kv_dtype
            if self._kv_quant:
                from .quant import QuantKV

                def zeros():
                    return QuantKV(
                        jnp.zeros(shape, jnp.int8),
                        jnp.zeros((shape[0], shape[2]), jnp.float32))

                out_sh = QuantKV(self._kv_sharding,
                                 self._kv_scale_sharding)
            else:

                def zeros():
                    return jnp.zeros(shape, dtype)

                out_sh = self._kv_sharding
            # cached: the pool shape is fixed per engine, and the
            # step-failure recovery path re-allocates repeatedly
            fn = self._pool_zeros_fn = jax.jit(
                zeros, out_shardings=out_sh)
        return fn()

    def _slot_shard(self, i):
        """The dp mesh shard that owns batch slot ``i``: slots divide
        into ``dp`` contiguous ranges of ``num_slots/dp`` rows,
        matching the ``P('dp', ...)`` sharding of every [B]-leading
        device array (always 0 when dp == 1)."""
        return int(i) // (self.num_slots // self.dp)

    def _reset_pools(self):
        """(Re)allocate the per-layer K/V pools and per-slot step
        state.  Also the failure-recovery path: a decode dispatch that
        dies AFTER consuming its donated pools leaves them deleted, so
        the loop handler must rebuild before the next tick.  In paged
        mode the block pool, prefix cache, and block tables are rebuilt
        with the arrays — cached prefixes die with the device rows
        they described."""
        import jax.numpy as jnp
        if self._paged:
            # +dp: each dp shard's pool range leads with one reserved
            # scratch block that its parked (inactive) slots read and
            # write through — their garbage compute may not touch a
            # block some live request owns, and under shard_map a
            # slot can only address rows inside its OWN shard's range
            # (dp == 1: one scratch block, physical row 0, as before)
            shape = (self._kv_managed + self.dp, self._bs, self._nh,
                     self._hd)
            self.block_pool = BlockPool(
                self._kv_managed + self.dp, self._bs,
                reserved_blocks=1, shards=self.dp,
                # chaos-harness hook: a scheduled "pool_exhaust" tick
                # turns this alloc into NoFreeBlocks (no-op when no
                # injector is attached)
                fault_hook=lambda n: self._fault("pool_exhaust"))
            self.prefix_cache = PrefixCache(
                self.block_pool,
                evict_hook=(self._offload_demote_hook
                            if self.host_store is not None else None)) \
                if self._prefix_enabled else None
            # pending demote gathers die with the pools they read
            # (step-failure recovery re-allocates) — drop, don't flush
            self._offload_pending = []
            self._offload_pending_keys = set()
            # per-slot scratch row: slot i belongs to dp shard
            # i // (num_slots/dp) and parks on THAT shard's reserved
            # row (all zeros at dp == 1); a parked/padded table entry
            # is this row, never a literal 0
            self._slot_scratch = np.asarray(
                [self.block_pool.scratch_row(self._slot_shard(i))
                 for i in range(self.num_slots)], np.int32)
            self._block_tables = np.repeat(
                self._slot_scratch[:, None], self._bps, axis=1).copy()
            self._slot_blocks = [[] for _ in range(self.num_slots)]
        else:
            shape = (self.num_slots, self.max_seq_len, self._nh,
                     self._hd)
        self.k_pools = [self._alloc_pool(shape)
                        for _ in self.model.blocks]
        self.v_pools = [self._alloc_pool(shape)
                        for _ in self.model.blocks]
        # host-side per-slot step state: in host sample_mode these ship
        # to device every tick; in device mode they are MIRRORS of the
        # device-resident cursors, re-uploaded only when an admission /
        # eviction / chunk dirties them (_push_state)
        self._pos = np.zeros(self.num_slots, np.int32)
        self._cur_tok = np.zeros((self.num_slots, 1), np.int32)
        # per-slot sampling lanes (device mode): temperature 0 is the
        # greedy sentinel, seed words feed core/rng.request_key, and
        # _sctr tracks each request's emitted-token count — the rng
        # fold counter that makes a seed reproduce across restarts
        self._temp = np.zeros(self.num_slots, np.float32)
        self._topk = np.zeros(self.num_slots, np.int32)
        self._topp = np.ones(self.num_slots, np.float32)
        self._seed_lo = np.zeros(self.num_slots, np.uint32)
        self._seed_hi = np.zeros(self.num_slots, np.uint32)
        self._sctr = np.zeros(self.num_slots, np.int32)
        # device-side stop-condition lanes: per-slot eos id (-1 =
        # none) and remaining token budget — a lane whose budget hits
        # zero freezes inside the dispatch, which is what makes
        # dispatching tick N+1 before consuming tick N safe
        self._eos = np.full(self.num_slots, -1, np.int32)
        self._rem = np.zeros(self.num_slots, np.int32)
        # per-slot LoRA lane (0 = base model); mirrors like the rest
        self._aid = np.zeros(self.num_slots, np.int32)
        self._dev_state = None   # device handles of the step state
        self._state_dirty = True  # device copies stale vs the mirrors
        self._ring = []  # dispatched-but-unconsumed ticks, oldest
        #   first (async_depth > 1); recovery and shutdown clear it —
        #   the dropped handles die with the rebuilt pools

    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens=16, eos_token_id=None,
               timeout=None, temperature=1.0, top_k=0, top_p=1.0,
               seed=None, priority=0, tenant=None, adapter=None):
        """Queue one generation request; returns its Request handle
        (block on ``request.result()``).

        ``priority``: higher-priority requests are served first and
        may PREEMPT running lower-priority streams under slot/KV
        pressure (``Engine(preemption=...)``).  ``tenant``: the
        weighted-fair / rate-limit accounting bucket
        (``Engine(tenants=...)``); None = the default tenant.

        Overload shedding happens HERE, at the edge: ``QueueFull``
        (queue at max_queue), ``RateLimited`` (tenant bucket empty),
        and ``DeadlineShed`` (the measured drain rate says the
        deadline is already unmeetable) all carry an honest
        ``retry_after`` estimate."""
        if self._draining:
            raise QueueFull(
                "engine draining: stop(drain=True) in progress — no "
                "new admissions", retry_after=None)
        if temperature <= 0:
            raise ValueError(
                f"temperature must be > 0, got {temperature} (greedy is "
                "the default when no sampling params are set)")
        if top_p <= 0 or top_p > 1:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        # coerce in the CALLER's thread: a bad eos/seed must fail this
        # submit, not crash the shared engine loop mid-decode
        try:
            eos_token_id = None if eos_token_id is None \
                else int(eos_token_id)
            seed = None if seed is None else int(seed)
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"eos_token_id/seed must be ints or None: {e}") from None
        if seed is not None and not 0 <= seed < MAX_SEED:
            raise ValueError(
                f"seed must be in [0, 2**63), got {seed}: the device "
                "sampling key derivation packs the seed into two "
                "32-bit words, and the host rng rejects negatives too")
        if adapter is not None:
            if self.adapters is None:
                raise UnknownAdapter(
                    f"adapter {adapter!r} requested but this engine "
                    "serves none (Engine(adapters=... / "
                    "max_adapters=N))")
            adapter = str(adapter)
            self.adapters.lane(adapter)  # raises UnknownAdapter now,
            #   in the caller's thread, instead of failing mid-admit
        req = Request(prompt, max_new_tokens, eos_token_id=eos_token_id,
                      timeout=timeout, temperature=temperature,
                      top_k=top_k, top_p=top_p, seed=seed,
                      priority=priority, tenant=tenant, adapter=adapter)
        total = len(req.prompt) + req.max_new_tokens
        margin = self._spec_k or 0
        if total + margin > self.max_seq_len:
            spec_note = (f" + spec_k ({margin}) speculative window "
                         "margin" if margin else "")
            raise ValueError(
                f"prompt ({len(req.prompt)}) + max_new_tokens "
                f"({req.max_new_tokens}){spec_note} = {total + margin} "
                f"exceeds the slot cache length ({self.max_seq_len})")
        # per-tenant token bucket: sustained over-rate traffic is
        # turned away before it can occupy queue places
        bucket = self._buckets.get(req.tenant)
        if bucket is not None:
            if req.cost_tokens > bucket.burst:
                # no amount of waiting admits a request larger than
                # the bucket itself — a finite Retry-After here would
                # be a lie that livelocks a well-behaved client
                self._m_shed_rate.inc()
                self.tracer.instant(
                    "req.shed", cat="request", req=req.id,
                    reason="rate_limited", tenant=req.tenant)
                raise RateLimited(
                    f"request {req.id} costs {req.cost_tokens} tokens"
                    f" but tenant {req.tenant!r}'s bucket holds at "
                    f"most {bucket.burst:g} — it can never be "
                    "admitted under this rate limit (split the "
                    "request or raise the tenant's burst)",
                    retry_after=None)
            wait = bucket.take(req.cost_tokens)
            if wait is not None:
                self._m_shed_rate.inc()
                self.tracer.instant(
                    "req.shed", cat="request", req=req.id,
                    reason="rate_limited", tenant=req.tenant,
                    retry_after_s=round(wait, 3))
                raise RateLimited(
                    f"tenant {req.tenant!r} over its token rate "
                    f"({self._tenant_policies[req.tenant].rate:g} "
                    f"tok/s): request {req.id} needs "
                    f"{req.cost_tokens} tokens, bucket refills in "
                    f"{wait:.2f}s", retry_after=round(wait, 3))
        # deadline-aware shedding: once the drain rate is measured, a
        # request whose wait estimate already blows its deadline is
        # rejected NOW with the honest backoff, instead of timing out
        # in queue (or worse, decoding for a caller that gave up)
        if self._shed_deadlines and req.deadline is not None:
            est = self.estimate_queue_wait(priority=req.priority)
            budget = req.deadline - req.submitted_at
            if est is not None and est > budget:
                if bucket is not None:
                    bucket.refund(req.cost_tokens)  # did no work —
                    #   a shed must not also drain the rate budget
                retry = round(est - budget, 3)
                self._m_shed_deadline.inc()
                self.tracer.instant(
                    "req.shed", cat="request", req=req.id,
                    reason="deadline", est_wait_s=round(est, 3),
                    retry_after_s=retry)
                raise DeadlineShed(
                    f"request {req.id} cannot meet its {budget:.2f}s "
                    f"deadline: estimated queue wait {est:.2f}s at "
                    "the measured drain rate; retry after "
                    f"{retry:.2f}s", retry_after=retry)
        # instant BEFORE put: once the request is in the queue the
        # engine thread may admit (even first-token) it concurrently,
        # and the ts-sorted timeline must keep queued -> admitted order
        self.tracer.instant("req.queued", cat="request", req=req.id,
                            prompt=int(len(req.prompt)),
                            max_new=req.max_new_tokens,
                            priority=req.priority, tenant=req.tenant,
                            adapter=req.adapter)
        if adapter is not None:
            # pin AFTER every shed check — a shed submit must not
            # leak a lane reference.  The pin drops via the request's
            # finish callback; every terminal path (evict, queue
            # timeout/expire, drain, Migrated) runs _finish.
            req._adapter_id = self.adapters.pin(adapter)
            req._finish_cbs.append(
                lambda _r, _n=adapter: self.adapters.unpin(_n))
        try:
            self.queue.put(req)
        except QueueFull as e:
            if adapter is not None:
                self.adapters.unpin(adapter)  # never queued — the
                #   finish callback will not run
            if bucket is not None:
                bucket.refund(req.cost_tokens)  # see deadline shed
            self._m_shed_queue.inc()
            e.retry_after = self._queue_full_retry_after()
            self.tracer.instant("req.shed", cat="request",
                                req=req.id, reason="queue_full",
                                retry_after_s=e.retry_after)
            raise
        self._m_reqs.inc()
        self._m_queue.set(self.queue.depth())
        self._wake.set()  # event-driven wake: an idle loop admits
        #   this request now, not up to a poll interval later
        return req

    # ------------------------------------------------------------------
    def _p_list(self):
        """Parameter arrays in pnames order, snapshotted once — the
        decode tick is per-token hot path, and the ~n_params dict walk
        never changes after init.  Call refresh_params() after mutating
        weights (quantization, checkpoint load) mid-serving."""
        if self._p_arrays is None:
            self._p_arrays = [self._params[k]._data
                              for k in self._pnames]
        return self._p_arrays

    def _b_list(self):
        """Buffer arrays sorted by name — every compiled path here
        (prefill, bucketed prefill, slot decode) orders buffers as
        sorted(named_buffers()), so one snapshot serves all three."""
        if self._b_arrays is None:
            bufs = dict(self.model.named_buffers())
            self._b_arrays = [bufs[k]._data for k in sorted(bufs)]
        return self._b_arrays

    def refresh_params(self):
        """Re-snapshot param/buffer handles after external weight
        mutation (the compiled programs themselves are keyed on names
        and dtypes and survive value changes).  Cached prefixes are
        K/V computed under the OLD weights — an adopter would silently
        decode against stale state — so the prefix cache is flushed
        (blocks still referenced by in-flight slots stay alive until
        their eviction)."""
        self._p_arrays = None
        self._b_arrays = None
        if self._paged and self.prefix_cache is not None:
            self.prefix_cache.clear()

    # -- LoRA lane plumbing (serving/lora.py) --------------------------
    def _lora_key(self, key):
        """Extend a compiled-path cache key with the adapter-bank
        geometry.  Adapter IDENTITY is data (the per-slot lane index);
        only n_lanes/r_max — fixed at construction — shape the trace,
        so loading adapter #2, #3, ... never mints a new program."""
        if self.adapters is None:
            return key
        return key + (("lora", self.adapters.n_lanes,
                       self.adapters.r_max),)

    def _lora_args_state(self, st):
        """Trailing ``*lora`` operands of a fused dispatch: the
        device-resident per-slot lane ids plus the two banks.  Empty
        when this engine serves no adapters — adapter-free engines
        trace exactly the programs they always traced."""
        if self.adapters is None:
            return ()
        return (st["aid"], self.adapters.a_bank, self.adapters.b_bank)

    def _lora_args_slot(self, req):
        """B=1 prefill/chunk variant: one slot's lane as a [1] lane
        array (prefill programs are per-request, so the lane rides the
        call instead of the pooled state)."""
        if self.adapters is None:
            return ()
        import jax.numpy as jnp
        return (jnp.asarray([req._adapter_id], jnp.int32),
                self.adapters.a_bank, self.adapters.b_bank)

    # -- overload protection: drain estimate / shedding / faults -------
    # drain-rate staleness horizon: entries older than this are
    # dropped, and a window whose NEWEST entry is older reads None —
    # an idle gap between bursts must not stretch the measured span
    # (rate would collapse by orders of magnitude and every deadline
    # submit after the gap would be spuriously shed)
    _RATE_HORIZON_S = 10.0

    def drain_rate(self, now=None):
        """Measured decode drain rate (tokens/sec) over the recent
        emitting-tick window; None until at least two emitting ticks
        exist inside the staleness horizon.  The denominator of every
        Retry-After the engine computes — honest because it is
        measured, not configured."""
        now = time.monotonic() if now is None else now
        with self._ovl_lock:
            snap = list(self._rate_win)
        win = [w for w in snap
               if now - w[0] <= self._RATE_HORIZON_S]
        if len(win) < 2:
            return None
        span = win[-1][0] - win[0][0]
        if span <= 1e-3:
            return None
        # tokens strictly after the window's first stamp, over the
        # stamped span — the first entry only anchors the clock
        return sum(n for _, n in win[1:]) / span

    def estimate_queue_wait(self, priority=0):
        """Seconds until a request submitted NOW at ``priority`` would
        reach a slot, from the measured drain rate: (in-flight
        remaining + queued work at its priority or above) / rate.
        None while the rate is unmeasured (a cold engine never
        sheds); 0.0 when nothing queues ahead AND the request would
        be placed next tick anyway — a free slot exists, or priority
        preemption would evict a lower-priority stream for it — so a
        partially-loaded engine never sheds against work it would not
        actually wait for."""
        rate = self.drain_rate()
        if rate is None or rate <= 0:
            return None
        backlog = self.queue.backlog_tokens(min_priority=priority)
        # snapshot the request refs ONCE: submit() runs on handler
        # threads while the engine thread evicts, so re-reading
        # slot.request after a None-check could observe the eviction
        # mid-expression
        reqs = [r for r in (s.request
                            for s in self.scheduler.busy_slots())
                if r is not None]
        if backlog == 0:
            if len(reqs) < self.num_slots:
                return 0.0
            if self._preemption and any(r.priority < priority
                                        for r in reqs):
                return 0.0
        # with preemption on, strictly-lower-priority in-flight
        # streams are not work this request waits behind — it would
        # evict them — so only same-or-higher-priority remaining
        # counts toward the estimate
        inflight = sum(r.remaining for r in reqs
                       if not self._preemption
                       or r.priority >= priority)
        return (inflight + backlog) / rate

    def _queue_full_retry_after(self):
        """Honest 503 backoff for a full queue: the measured time for
        ONE queue place to drain (total backlog / drain rate, per
        queued request); 1.0s when the rate is still unmeasured."""
        rate = self.drain_rate()
        depth = self.queue.depth()
        if rate is None or rate <= 0 or depth == 0:
            return 1.0
        return round(max(self.queue.backlog_tokens() / rate / depth,
                         0.05), 3)

    def _fault(self, site):
        """Consult the fault injector at a named failure point: a
        no-op (None injector or unscheduled tick) costs one attribute
        read; a scheduled site counts, traces, and performs its
        action (which may raise into the step-failure recovery)."""
        f = self.faults
        if f is not None and f.scheduled(site, self.tick_no):
            self._m_faults.inc()
            self.tracer.instant("fault.injected", cat="fault",
                                site=site, tick=self.tick_no)
            f.fire(site, self.tick_no, self)

    def _preempt_history(self):
        """Locked snapshot of the preemption/requeue ring (handler and
        watchdog threads read it while the engine thread appends)."""
        with self._ovl_lock:
            return list(self._preempt_log)

    def _post_admit(self, admitted, timed_out, tr):
        """Shared post-admission phase of both tick paths.  Reconciles
        the admitted list against the preemption round — a handler
        thread can land a higher-priority submit in the window between
        the admit and preemption phases, so a slot admitted earlier
        THIS tick may since have been evicted or rebound; keeping one
        entry per still-bound slot is what stops the prefill loop from
        binding a consumed ``_kv_plan`` twice or dereferencing a freed
        slot — then emits the admitted/resumed instants and accounts
        the timeouts.  Returns the reconciled admitted list."""
        uniq = []
        for slot in admitted:
            if slot.request is not None and slot not in uniq:
                uniq.append(slot)
        for slot in uniq:
            req = slot.request
            tr.instant("req.admitted", cat="request",
                       req=req.id, slot=slot.index)
            if req.preemptions:
                self._m_resumed.inc()
                tr.instant("req.resumed", cat="request", req=req.id,
                           slot=slot.index,
                           tokens=len(req.generated))
        if timed_out:
            self._m_timeout.inc(len(timed_out))
            self._m_done.inc(len(timed_out))
            for req in timed_out:
                self._rngs.pop(req.id, None)  # a preempted-then-
                #   expired request may hold a host rng stream
                tr.instant("req.evicted", cat="request", req=req.id,
                           reason="timeout")
        return uniq

    # -- priority preemption -------------------------------------------
    def _preempt(self, slot, tr):
        """Evict a RUNNING request mid-stream under priority pressure
        and requeue it with its emitted tokens preserved.  Paged mode
        first inserts every FULL block of the computed history
        (prompt + emitted-so-far — ``slot.pos`` rows of K/V) into the
        prefix cache, so re-admission adopts the span and the resume
        skips re-prefill; the frozen ``req._ctx`` snapshot is what a
        re-admission prefills.  The resumed stream is token-identical
        to an uninterrupted run: greedy trivially, sampled because
        the device key folds the emitted-token counter (the next draw
        is draw #len(generated) either way) and the host rng stream
        stays alive in ``_rngs``.  Caller must have DRAINED the async
        ring: an in-flight lane whose request vanished un-done would
        otherwise raise the consume-side drift check."""
        req = slot.request
        i = slot.index
        ctx = (np.concatenate([req.prompt,
                               np.asarray(req.generated, np.int32)])
               if req.generated else req.prompt)
        if self._paged and self.prefix_cache is not None \
                and not req._adapter_id:
            # slot.pos rows of K/V are computed (decoding slots: the
            # last emitted token's row is pending, exactly pos rows
            # valid; prefilling slots: pos == prefilled) — only full
            # blocks under that bound are adoptable.  Adapter lanes
            # never share: LoRA on out_proj shifts the residual
            # stream, so layers >= 1 K/V depend on the adapter
            n_full = min(slot.pos // self._bs,
                         len(self._slot_blocks[i]))
            if n_full:
                self.prefix_cache.insert(ctx,
                                         self._slot_blocks[i][:n_full])
        plan = getattr(req, "_kv_plan", None)
        if plan is not None:
            # admitted-but-not-yet-prefilled victim (a concurrent
            # higher-priority submit landed between admission and
            # prefill): its gate reservation was never bound to the
            # slot, so return it here — adopted prefix refs fall back
            # to the cache's own, fresh blocks free
            del req._kv_plan
            if self._paged:
                pctx, pfresh, _ = plan
                self.block_pool.decref(pctx + pfresh)
        self.scheduler.release(slot)
        self._release_slot_kv(i)
        self._park_state(i)
        req._ctx = ctx
        req.preemptions += 1
        self.queue.requeue(req)
        self._m_preempt.inc()
        with self._ovl_lock:
            self._preempt_log.append({
                "tick": self.tick_no, "request": req.id, "slot": i,
                "priority": req.priority, "tenant": req.tenant,
                "generated": len(req.generated),
                "preemptions": req.preemptions,
            })
        tr.instant("req.preempted", cat="request", req=req.id,
                   slot=i, tokens=len(req.generated),
                   priority=req.priority)

    def _preempt_round(self, now, tr):
        """Admission-phase preemption loop: while the best queued
        priority outranks a running request and admission is blocked
        — every slot busy, or the paged gate just declined the head
        for lack of blocks — evict the lowest-priority busy slot
        (tie-break: most recently admitted, least sunk work) and
        retry admission.  Returns (admitted_slots, timed_out,
        emitted) — emitted counts tokens from any async-ring drain
        the eviction forced."""
        admitted, timed_out, emitted = [], [], 0
        if not self._preemption or self._draining:
            return admitted, timed_out, emitted
        for _ in range(2 * self.num_slots):
            pri = self.queue.best_priority()
            if pri is None:
                break
            blocked = (self.scheduler.free_count() == 0
                       or (self._paged and self._gate_declined))
            if not blocked:
                break
            victims = [s for s in self.scheduler.busy_slots()
                       if s.request is not None
                       and s.request.priority < pri]
            if not victims:
                break
            victim = min(victims,
                         key=lambda s: (s.request.priority, -s.seq))
            if self._ring:
                # consume in-flight ticks first: the victim's device
                # lane is NOT done, and the consume-side drift check
                # must never see a vanished live request
                emitted += self._drain_ring(tr)
                vr = victim.request
                if vr is None or vr.priority >= pri \
                        or self.scheduler.free_count() > 0:
                    # the drain finished the victim — or freed some
                    # OTHER slot: admit into the capacity that now
                    # exists instead of evicting a live stream for
                    # it, then re-probe from the top
                    self._gate_declined = False
                    more, t2 = self.scheduler.admit(
                        now,
                        gate=self._kv_gate if self._paged else None)
                    admitted += more
                    timed_out += t2
                    continue
            self._preempt(victim, tr)
            self._gate_declined = False
            more, t2 = self.scheduler.admit(
                now, gate=self._kv_gate if self._paged else None)
            admitted += more
            timed_out += t2
        return admitted, timed_out, emitted

    # -- KV block migration --------------------------------------------
    # A migration is a block-table rewrite plus a bytes transfer: the
    # source gathers its slot's FULL blocks device->host
    # (kvcache.export_blocks), tears the slot down exactly like a
    # preemption (prefix insert, release, park) but finishes the
    # request with ``Migrated`` instead of requeueing it, and the
    # resume snapshot (prompt, emitted tokens, sampling params, the
    # EFFECTIVE seed, host-rng state) rides alongside the bytes.  The
    # destination scatters the blocks into its own pool
    # (kvcache.import_blocks), registers them under its prefix trie,
    # and queues an equivalent Request — whose normal admission
    # prefix-matches the adopted blocks and binds the sample state at
    # fold-counter len(generated), i.e. the stream resumes through the
    # SAME proven preemption-resume path, token-identically.
    #
    # All four public entry points (migrate_out / migrate_in /
    # export_prefix / import_prefix) are thread-safe: they register a
    # _MigrateDemand and the ENGINE THREAD services it at the next
    # tick boundary (``_service_migrations``), after draining any
    # in-flight async ring — pool and slot state stay single-writer.
    # Fault sites: ``migrate_export`` declines an export with the
    # stream untouched, ``migrate_import`` rolls the destination's
    # fresh allocation back to refcount 0 (it adopts nothing), and
    # ``migrate_wire`` is thrown by transports between the two.

    def _register_demand(self, demand):
        with self._mig_lock:
            self._migrate_demands.append(demand)
        self._wake.set()
        return demand

    def _migrate_pending(self):
        with self._mig_lock:
            return len(self._migrate_demands)

    def _migrate_actionable(self):
        """True when a registered demand can make progress on the next
        tick — the idle loop's wake condition.  A waiting export (no
        eligible victim yet) is excluded: whatever makes it actionable
        (a submit, an import) wakes the loop itself."""
        with self._mig_lock:
            return any(not d.waiting for d in self._migrate_demands)

    def _migration_history(self):
        """Locked snapshot of the migration ring (handler threads read
        it for ``/debug/requests`` while the engine thread appends)."""
        with self._mig_lock:
            return list(self._migration_log)

    def _await_demand(self, d, wait, timeout):
        if not wait:
            return d
        try:
            return d.wait(timeout)
        except TimeoutError:
            # withdraw the order if the engine has not yet picked it
            # up; if servicing already started the verdict lands in
            # the demand unobserved — an exported stream's payload
            # still reaches its waiter via Migrated.emitted salvage
            with self._mig_lock:
                if d in self._migrate_demands:
                    self._migrate_demands.remove(d)
            raise

    def live_request_ids(self):
        """Ids of the requests currently BOUND to slots (prefilling
        included), in slot order — the SIGTERM drain's worklist: each
        one is exported to a peer via ``migrate_out(request_id=...)``
        as soon as it is decoding.  Queued-but-unadmitted requests
        are deliberately absent: a draining engine admits nothing, so
        they have emitted nothing and fail over with zero lost work.
        Thread-safe (``busy_slots`` snapshots under the scheduler
        lock)."""
        return [s.request.id for s in self.scheduler.busy_slots()
                if s.request is not None]

    def migrate_out(self, request_id=None, min_tokens=1,
                    deliver="return", wait=True, timeout=30.0):
        """Export a LIVE decoding stream off this engine.  With
        ``request_id=None`` the engine picks a victim (lowest
        priority, most work remaining); otherwise the named request is
        exported once it is decoding with ``min_tokens`` emitted.  The
        stream's waiter unblocks with ``Migrated`` (its ``emitted``
        always carries the tokens generated here).  ``deliver``:

        - ``"return"`` — the migration payload is this call's return
          value (``{"payload": ..., "generated": [...], "completed":
          False}``); the waiter's Migrated carries payload=None.  The
          HTTP export handler path.
        - ``"error"`` — the payload rides INSIDE the waiter's Migrated
          exception and this call returns payload=None; whoever holds
          the stream (the router's generate loop) owns the import.
          Exactly-once by construction: there is a single payload
          holder either way.

        A request that finishes before the export lands returns
        ``{"completed": True, "generated": [...], "payload": None}``.
        A scheduled ``migrate_export`` fault raises here and leaves
        the stream running untouched."""
        if deliver not in ("return", "error"):
            raise ValueError(f"deliver must be 'return' or 'error', "
                             f"got {deliver!r}")
        d = self._register_demand(_MigrateDemand(
            "out", request_id=request_id, min_tokens=int(min_tokens),
            deliver=deliver))
        return self._await_demand(d, wait, timeout)

    def migrate_in(self, payload, wait=True, timeout=30.0):
        """Adopt a migrated stream: scatter its KV blocks into this
        engine's pool + prefix trie (all-or-nothing) and queue an
        equivalent Request that resumes the stream token-identically.
        Accepts either a live payload (``kv["data"]`` an ndarray) or
        the JSON wire form (``kv["data_b64"]`` — decoded here, under
        the ``migrate.wire`` span, so the byte-level transfer cost is
        attributable in traces).  Returns ``{"request": Request,
        "blocks": n}`` — the caller streams ``request.result()`` like
        any submit.  Raises ValueError (geometry mismatch / malformed
        payload), QueueFull (draining or full queue), or an injected
        ``migrate_import`` fault; in every failure the destination
        owns nothing."""
        kv = payload.get("kv") if isinstance(payload, dict) else None
        with self.tracer.span(
                "migrate.wire", cat="serving",
                blocks=int(kv.get("n_blocks") or 0) if kv else 0):
            if not isinstance(payload, dict) \
                    or not isinstance(payload.get("request"), dict):
                raise ValueError(
                    "migration payload must carry a 'request' dict "
                    "(see Engine.migrate_out)")
            if kv is not None and "data_b64" in kv:
                from .kvcache import payload_from_json
                payload = payload_from_json(payload)
                kv = payload.get("kv")
            if not payload["request"].get("prompt"):
                raise ValueError(
                    "migration payload request has no prompt")
            if kv is not None and kv.get("n_blocks") \
                    and kv.get("data") is None:
                raise ValueError(
                    "migration payload kv names n_blocks but carries "
                    "no data")
        d = self._register_demand(_MigrateDemand("in", payload=payload))
        return self._await_demand(d, wait, timeout)

    def export_prefix(self, tokens, wait=True, timeout=30.0):
        """Cross-replica prefix warming, export side: gather the
        longest cached prefix of ``tokens`` from this engine's trie.
        Returns a migration payload with ``request=None`` and a
        ``prefix`` token list (import with ``import_prefix``), or None
        when nothing is cached (or the engine is contiguous/has no
        trie)."""
        d = self._register_demand(_MigrateDemand(
            "prefix_out", tokens=[int(t) for t in tokens]))
        return self._await_demand(d, wait, timeout)

    def import_prefix(self, payload, wait=True, timeout=30.0):
        """Cross-replica prefix warming, import side: adopt a peer
        trie's exported blocks into this engine's prefix cache, so the
        next admission of a prompt sharing that prefix skips its
        prefill.  Returns ``{"blocks": n, "tokens": n*block_size}``
        (zeros when the payload is empty or this engine cannot hold
        it).  Accepts live or JSON wire form, like ``migrate_in``."""
        if payload is None:
            return {"blocks": 0, "tokens": 0}
        kv = payload.get("kv") if isinstance(payload, dict) else None
        with self.tracer.span(
                "migrate.wire", cat="serving",
                blocks=int(kv.get("n_blocks") or 0) if kv else 0):
            if kv is not None and "data_b64" in kv:
                from .kvcache import payload_from_json
                payload = payload_from_json(payload)
        d = self._register_demand(_MigrateDemand(
            "prefix_in", payload=payload))
        return self._await_demand(d, wait, timeout)

    # -- hot adapter load / unload (serving/lora.py) -------------------
    def load_adapter(self, name, adapter, wait=True, timeout=30.0):
        """Hot-load a LoRA adapter under ``name`` while serving.  The
        swap rides the migration-demand machinery: the ENGINE THREAD
        services it at the next tick boundary after draining any
        in-flight async ring, so the bank write is single-writer and
        no dispatched tick straddles it.  Pure data movement — bank
        shapes are fixed at construction, so the compile probe sees
        nothing.  Raises RegistryFull (no free lane), ValueError
        (shape mismatch / duplicate name), or an injected
        ``adapter_load`` fault (banks and inventory untouched)."""
        if self.adapters is None:
            raise RuntimeError(
                "this engine serves no adapters: construct with "
                "Engine(adapters=...) or max_adapters=N to reserve "
                "bank lanes")
        if not isinstance(adapter, LoRAAdapter):
            raise TypeError(
                f"expected LoRAAdapter, got {type(adapter).__name__}")
        d = self._register_demand(_MigrateDemand(
            "adapter_load", name=str(name), adapter=adapter))
        return self._adapter_await(d, wait, timeout)

    def unload_adapter(self, name, wait=True, timeout=30.0):
        """Unload adapter ``name``: refuse (AdapterInUse) while any
        in-flight request pins it, else zero its lane and free it.
        Same tick-boundary servicing as ``load_adapter``."""
        if self.adapters is None:
            raise RuntimeError("this engine serves no adapters")
        d = self._register_demand(_MigrateDemand(
            "adapter_unload", name=str(name)))
        return self._adapter_await(d, wait, timeout)

    def _adapter_await(self, d, wait, timeout):
        t = self._thread
        if t is None or not t.is_alive():
            # no background loop running: service inline on the
            # caller's thread (the single-writer rule holds — nothing
            # else is stepping; synchronous drivers call load/unload
            # between their own step() calls)
            self._service_migrations(self.tracer)
        return self._await_demand(d, wait, timeout)

    def _service_adapter(self, d, tr):
        """Engine-thread half of load/unload_adapter.  Drains the
        async ring first — a dispatched tick read the OLD banks and
        must be consumed against them before the lane flips.  Handles
        its own failure (d.fail) so the drained-token count always
        reaches the tick accounting.  Returns tokens emitted by the
        drain."""
        emitted = self._drain_ring(tr) if self._ring else 0
        name = d.args["name"]
        try:
            with tr.span("lora.swap", cat="serving", op=d.kind,
                         adapter=name):
                self._fault("adapter_load")
                if d.kind == "adapter_load":
                    lane = self.adapters.load(name, d.args["adapter"])
                    tr.instant("adapter.loaded", cat="serving",
                               adapter=name, lane=lane)
                else:
                    lane = self.adapters.unload(name)
                    tr.instant("adapter.unloaded", cat="serving",
                               adapter=name, lane=lane)
            d.complete({"name": name, "lane": lane})
        except Exception as e:  # noqa: BLE001 — verdict channel
            d.fail(e)
        return emitted

    def _service_migrations(self, tr):
        """Engine-thread service point, called at the top of both tick
        paths: pop the registered demands, act on each (an "out" whose
        target is not yet exportable waits for a later tick), and
        never let a per-demand failure — injected or organic — escape
        into step recovery.  Returns tokens emitted by any ring drain
        an export forced."""
        with self._mig_lock:
            if not self._migrate_demands:
                return 0
            demands = list(self._migrate_demands)
            self._migrate_demands = []
        emitted = 0
        keep = []
        for d in demands:
            try:
                if d.kind == "out":
                    verdict, n = self._service_migrate_out(d, tr)
                    emitted += n
                    if verdict == "wait":
                        d.waiting = True
                        keep.append(d)
                elif d.kind == "in":
                    self._service_migrate_in(d, tr)
                elif d.kind in ("adapter_load", "adapter_unload"):
                    emitted += self._service_adapter(d, tr)
                elif d.kind == "prefix_out":
                    self._service_prefix_out(d, tr)
                else:
                    self._service_prefix_in(d, tr)
            except Exception as e:  # noqa: BLE001 — verdict channel
                d.fail(e)
        if keep:
            with self._mig_lock:
                # demands registered while servicing appended to the
                # emptied list; waiting orders go back ahead of them
                self._migrate_demands = keep + self._migrate_demands
        return emitted

    def _find_out_candidate(self, d):
        """Resolve an export demand to its current (slot, request).
        Unpinned demands pick a victim among decoding slots meeting
        the min_tokens bar — lowest priority first, then most work
        remaining, then lowest slot index (deterministic under a
        seeded schedule) — and pin the Request HANDLE so later ticks
        track the same stream even across its eviction (a stream that
        finishes before the export lands must resolve as completed,
        not vanish).  Returns (None, req) when the request exists but
        is not in a slot, (None, None) when unknown."""
        req = d.args.get("req")
        if req is not None:
            return self.scheduler.find(req.id), req
        rid = d.args["request_id"]
        if rid is None:
            cands = [s for s in self.scheduler.busy_slots()
                     if s.request is not None and s.decoding
                     and not s.request._adapter_id
                     and len(s.request.generated)
                     >= d.args["min_tokens"]]
            if not cands:
                return None, None
            victim = min(cands, key=lambda s: (s.request.priority,
                                               -s.request.remaining,
                                               s.index))
            d.args["req"] = victim.request
            return victim, victim.request
        slot = self.scheduler.find(rid)
        if slot is not None:
            d.args["req"] = slot.request
            return slot, slot.request
        for r in self.queue.pending():
            if r.id == rid:
                d.args["req"] = r
                return None, r
        return None, None

    @staticmethod
    def _finish_out_done(d, req):
        """The export target reached a terminal state before the
        export landed: a clean finish completes the demand (nothing
        to migrate — the tokens are all here), a failed or
        already-migrated stream fails it with that verdict."""
        if req.error is not None:
            d.fail(req.error)
        else:
            d.complete({"completed": True, "payload": None,
                        "generated": [int(t) for t in req.generated]})

    def _service_migrate_out(self, d, tr):
        """One export attempt.  Returns (verdict, emitted): verdict
        "wait" re-registers the demand for the next tick, "done" has
        completed or failed it."""
        slot, req = self._find_out_candidate(d)
        if req is None:
            if d.args["request_id"] is not None:
                d.fail(KeyError(
                    f"no live request {d.args['request_id']} to "
                    "migrate"))
                return "done", 0
            return "wait", 0  # no eligible victim yet
        if req.done():
            self._finish_out_done(d, req)
            return "done", 0
        if req._adapter_id:
            # the payload format carries no adapter identity — a
            # destination would resume through its BASE lane, silently
            # changing the model mid-stream.  The router's failover
            # path (re-submit prompt+emitted with model=) covers
            # adapter streams instead.
            d.fail(RuntimeError(
                f"request {req.id} decodes through adapter "
                f"{req.adapter!r}: KV migration does not carry "
                "adapter lanes — drain it, or let the caller fail "
                "over with prompt+emitted"))
            return "done", 0
        if slot is None or not slot.decoding \
                or len(req.generated) < d.args["min_tokens"]:
            return "wait", 0
        emitted = 0
        if self._ring:
            # freeze point: the slot's device cursor must be the
            # host-consumed view before its rows are gathered, and the
            # consume-side drift check must never see a vanished live
            # request — same discipline as preemption
            emitted += self._drain_ring(tr)
            if req.done():
                self._finish_out_done(d, req)
                return "done", emitted
            slot = self.scheduler.find(req.id)
            if slot is None or not slot.decoding:
                return "wait", emitted
        try:
            self._fault("migrate_export")
        except Exception as e:  # noqa: BLE001 — injected decline
            d.fail(e)  # the stream keeps running on this engine
            return "done", emitted
        payload = self._export_slot(slot, tr,
                                    deliver=d.args["deliver"])
        d.complete({
            "completed": False,
            "generated": list(payload["request"]["generated"]),
            "payload": payload if d.args["deliver"] == "return"
            else None})
        return "done", emitted

    def _export_slot(self, slot, tr, deliver):
        """Freeze + gather + tear down one decoding slot (ring already
        drained, fault site already consulted).  The teardown is
        preemption-shaped — full blocks into the trie (the source
        keeps the warm prefix), release, park — but terminal: the
        waiter unblocks with ``Migrated`` instead of the request
        requeueing."""
        req = slot.request
        i = slot.index
        ctx = (np.concatenate([req.prompt,
                               np.asarray(req.generated, np.int32)])
               if req.generated else req.prompt)
        kv = None
        n_full = 0
        with tr.span("migrate.export", cat="serving", req=req.id) as sp:
            if self._paged:
                # decoding slots hold exactly slot.pos computed rows
                # (the last emitted token's row is pending) — only
                # full blocks under that bound travel; the partial
                # tail is recomputed by the destination's
                # prefix-adoption prefill
                n_full = min(slot.pos // self._bs,
                             len(self._slot_blocks[i]))
                blocks = self._slot_blocks[i][:n_full]
                if n_full:
                    data = export_blocks(self.k_pools, self.v_pools,
                                         blocks)
                    kv = {"block_size": self._bs,
                          "num_heads": self._nh,
                          "head_dim": self._hd,
                          "n_layers": len(self.k_pools),
                          "dtype": self._kv_dtype_str,
                          "n_blocks": n_full}
                    if self._kv_quant:
                        # quantized export: codes + their per-block
                        # scales travel together
                        kv["data"], kv["scales"] = data
                    else:
                        kv["data"] = data
                if self.prefix_cache is not None and n_full:
                    self.prefix_cache.insert(ctx, blocks)
            rng = self._rngs.pop(req.id, None)
            # np.random.Generator state is a plain JSON-able dict of
            # Python ints — the destination rebuilds the exact stream
            rng_state = (rng.bit_generator.state
                         if rng is not None else None)
            payload = {
                "version": 1,
                "request": {
                    "source_id": req.id,
                    "prompt": [int(t) for t in req.prompt],
                    "generated": [int(t) for t in req.generated],
                    "max_new_tokens": req.max_new_tokens,
                    "eos_token_id": req.eos_token_id,
                    "temperature": req.temperature,
                    "top_k": req.top_k, "top_p": req.top_p,
                    # the EFFECTIVE seed: an unseeded sampled stream
                    # defaults to its request id, and the destination
                    # mints a NEW id — carrying the resolved value
                    # keeps the resumed draws identical either way
                    "seed": (int(req.sample_seed) if req.do_sample
                             else req.seed),
                    "priority": req.priority, "tenant": req.tenant,
                    "preemptions": req.preemptions,
                    "rng_state": rng_state,
                },
                "kv": kv,
            }
            sp.args.update(blocks=n_full, tokens=len(req.generated))
        self.scheduler.release(slot)
        self._release_slot_kv(i)
        self._park_state(i)
        self._m_kv_migrated.inc(n_full)
        self._m_done.inc()  # terminal HERE, like a timeout: keeps
        #   in-flight = submitted - completed consistent per engine
        with self._mig_lock:
            self._migration_log.append({
                "tick": self.tick_no, "dir": "out",
                "request": req.id, "blocks": n_full,
                "tokens": len(req.generated)})
        tr.instant("req.migrated_out", cat="request", req=req.id,
                   blocks=n_full, tokens=len(req.generated))
        req._finish(Migrated(
            f"request {req.id} migrated out after "
            f"{len(req.generated)} token(s)",
            payload=payload if deliver == "error" else None,
            emitted=req.generated))
        return payload

    def _adopt_blocks(self, kv, ctx, tr):
        """Validate + allocate + scatter + trie-adopt a payload's KV
        blocks (engine thread).  Returns the adopted block ids, or []
        when the payload carries none or this engine cannot hold them
        (contiguous layout / no trie — the request still imports
        whole, its admission re-prefills instead of adopting).
        All-or-nothing: a geometry mismatch, a scheduled
        ``migrate_import`` fault, or a scatter failure rolls the
        fresh allocation back to refcount 0 and raises — the
        destination owns nothing."""
        if kv is None or not kv.get("n_blocks"):
            return []
        if not self._paged or self.prefix_cache is None:
            return []
        n = int(kv["n_blocks"])
        # dtype FIRST, as its own machine-readable refusal: int8 codes
        # adopted by an fp engine (or fp rows by a quantized one)
        # would be garbage at best — peers must agree on kv_dtype
        # before geometry even matters
        peer_dtype = str(kv.get("dtype"))
        if peer_dtype != self._kv_dtype_str:
            raise KVDtypeMismatch(
                f"migration payload kv dtype {peer_dtype!r} does not "
                f"match this engine's {self._kv_dtype_str!r}: "
                "adopting nothing (peers must serve the same "
                "kv_dtype)")
        want = {"block_size": self._bs, "num_heads": self._nh,
                "head_dim": self._hd, "n_layers": len(self.k_pools)}
        got = {k: kv.get(k) for k in want}
        if got != want:
            raise ValueError(
                f"migration payload geometry {got} does not match "
                f"this engine ({want}): adopting nothing")
        # adopted blocks land in ONE dp shard (the trie is per-shard
        # so the whole run stays block-local): pick the emptiest
        shard = max(range(self.dp),
                    key=lambda d: self.block_pool.free_count(d))
        short = n - self.block_pool.free_count(shard)
        if short > 0:
            evicted = self.prefix_cache.evict(short, shard=shard)
            if evicted:
                self._m_prefix_evictions.inc(len(evicted))
        blocks = self.block_pool.alloc(n, shard=shard)
        #   may raise NoFreeBlocks
        try:
            self._fault("migrate_import")
            with tr.span("migrate.import", cat="serving", blocks=n):
                self.k_pools, self.v_pools = import_blocks(
                    self.k_pools, self.v_pools, blocks, kv["data"],
                    scales=kv.get("scales"))
            # hand ownership to the trie: insert takes one ref per
            # NEW node, then the alloc ref drops — the blocks are the
            # cache's exactly like a finished request's, and the
            # admission gate's prefix match re-refs them per adopter.
            # (A depth already cached keeps ITS block; ours frees at
            # the decref — same tokens, same content, consistent.)
            self.prefix_cache.insert(ctx, blocks)
        except BaseException:
            self.block_pool.decref(blocks)  # refcount 0, freed
            raise
        self.block_pool.decref(blocks)
        return blocks

    def _service_migrate_in(self, d, tr):
        """Adopt one migrated stream: blocks into pool+trie, then an
        equivalent Request through the normal queue — admission
        prefix-matches the adopted blocks and ``_bind_sample_state``
        rebinds the rng at fold counter len(generated), the proven
        preemption-resume path."""
        if self._draining:
            raise QueueFull("engine is draining: not accepting "
                            "migrations")
        payload = d.args["payload"]
        rq = payload["request"]
        generated = [int(t) for t in rq.get("generated") or []]
        ctx = [int(t) for t in rq["prompt"]] + generated
        blocks = self._adopt_blocks(payload.get("kv"), ctx, tr)
        req = Request(
            rq["prompt"], rq["max_new_tokens"],
            eos_token_id=rq.get("eos_token_id"),
            temperature=rq.get("temperature", 1.0),
            top_k=rq.get("top_k", 0), top_p=rq.get("top_p", 1.0),
            seed=rq.get("seed"), priority=rq.get("priority", 0),
            tenant=rq.get("tenant"))
        req.generated = generated
        req._ctx = np.asarray(ctx, np.int32)
        req.preemptions = int(rq.get("preemptions") or 0) + 1
        #   counts the handoff; admission emits req.resumed for it
        state = rq.get("rng_state")
        if state is not None and self.sample_mode == "host":
            g = np.random.default_rng(req.sample_seed)
            g.bit_generator.state = state
            self._rngs[req.id] = g
        self.queue.put(req)
        self._m_reqs.inc()
        with self._mig_lock:
            self._migration_log.append({
                "tick": self.tick_no, "dir": "in", "request": req.id,
                "source": rq.get("source_id"), "blocks": len(blocks),
                "tokens": len(generated)})
        tr.instant("req.migrated_in", cat="request", req=req.id,
                   source=rq.get("source_id"), blocks=len(blocks),
                   tokens=len(generated))
        d.complete({"request": req, "blocks": len(blocks)})

    def _service_prefix_out(self, d, tr):
        """Prefix-warming export: gather the trie's longest cached
        prefix of the demand's tokens.  Completes with None when
        nothing is cached."""
        tokens = d.args["tokens"]
        if not self._paged or self.prefix_cache is None:
            d.complete(None)
            return
        try:
            self._fault("migrate_export")
        except Exception as e:  # noqa: BLE001 — injected decline
            d.fail(e)
            return
        blocks, m = self.prefix_cache.match(tokens)
        # host-tier continuation: blocks this engine evicted to host
        # RAM still beat the peer's recompute — walk the store for
        # consecutive continuation entries past the device match
        host_parts = []
        if self.host_store is not None:
            from .offload import prefix_key
            i = m // self._bs
            limit = (len(tokens) - 1) // self._bs
            while i < limit:
                ent = self.host_store.get(
                    prefix_key(tokens, (i + 1) * self._bs))
                if ent is None:
                    break
                host_parts.append(ent)
                i += 1
        if not blocks and not host_parts:
            d.complete(None)
            return
        data = scales = None
        if blocks:
            try:
                with tr.span("migrate.export", cat="serving",
                             blocks=len(blocks), prefix=True):
                    data = export_blocks(self.k_pools, self.v_pools,
                                         blocks)
            finally:
                self.block_pool.decref(blocks)  # drop match's refs
            if self._kv_quant:
                data, scales = data
        if host_parts:
            hd = np.stack([p[0] for p in host_parts], axis=2)
            hs = (np.stack([p[1] for p in host_parts], axis=2)
                  if host_parts[0][1] is not None else None)
            data = (hd if data is None
                    else np.concatenate((data, hd), axis=2))
            if hs is not None:
                scales = (hs if scales is None
                          else np.concatenate((scales, hs), axis=2))
        n_blocks = len(blocks) + len(host_parts)
        m_total = m + len(host_parts) * self._bs
        tier = ("mixed" if blocks and host_parts
                else "host" if host_parts else "device")
        kv = {"block_size": self._bs, "num_heads": self._nh,
              "head_dim": self._hd, "n_layers": len(self.k_pools),
              "dtype": self._kv_dtype_str, "n_blocks": n_blocks}
        if self._kv_quant:
            kv["data"], kv["scales"] = data, scales
        else:
            kv["data"] = data
        payload = {
            "version": 1, "request": None,
            "prefix": [int(t) for t in tokens[:m_total]],
            "tier": tier,
            "kv": kv}
        self._m_kv_migrated.inc(n_blocks)
        with self._mig_lock:
            self._migration_log.append({
                "tick": self.tick_no, "dir": "prefix_out",
                "blocks": n_blocks, "tokens": m_total,
                "tier": tier})
        d.complete(payload)

    def _service_prefix_in(self, d, tr):
        """Prefix-warming import: adopt a peer trie's blocks.  The
        exported prefix covers exactly n_blocks * block_size tokens,
        so every block registers under the trie."""
        payload = d.args["payload"]
        tokens = [int(t) for t in payload.get("prefix") or []]
        blocks = self._adopt_blocks(payload.get("kv"), tokens, tr)
        if blocks:
            with self._mig_lock:
                self._migration_log.append({
                    "tick": self.tick_no, "dir": "prefix_in",
                    "blocks": len(blocks),
                    "tokens": len(blocks) * self._bs})
            tr.instant("prefix.warmed", cat="serving",
                       blocks=len(blocks))
        d.complete({"blocks": len(blocks),
                    "tokens": len(blocks) * self._bs if blocks else 0})

    # -- host-RAM offload tier (serving/offload.py) ---------------------
    def _offload_demote_hook(self, tokens, block):
        """PrefixCache evict hook: enqueue an async device gather of
        the dying block's rows BEFORE the pool reference drops.  The
        gather is dispatched HERE — jax arrays are immutable and
        device execution is in-order, so the snapshot stays consistent
        even though later dispatches donate the pools — but
        materialized (d2h) at the next tick boundary
        (``_service_offload``), double-buffered behind the next
        dispatch so the engine thread never blocks mid-tick.  A
        scheduled ``offload_demote`` fault, a duplicate content
        address, or any gather failure degrades to the pre-offload
        behavior: the block simply frees, the store sees nothing
        (the trie swallows hook exceptions for the same reason)."""
        if self.host_store is None:
            return
        try:
            self._fault("offload_demote")
        except Exception:
            return  # scheduled demote failure: free without spilling
        from .offload import prefix_key
        key = prefix_key(tokens)
        if key in self.host_store or key in self._offload_pending_keys:
            return  # content-addressed dedup: this prefix is parked
        import jax.numpy as jnp
        ids = jnp.asarray([int(block)], jnp.int32)
        if self._kv_quant:
            data = jnp.stack(
                [jnp.stack((jnp.take(kp.codes, ids, axis=0),
                            jnp.take(vp.codes, ids, axis=0)))
                 for kp, vp in zip(self.k_pools, self.v_pools)])
            scales = jnp.stack(
                [jnp.stack((jnp.take(kp.scale, ids, axis=0),
                            jnp.take(vp.scale, ids, axis=0)))
                 for kp, vp in zip(self.k_pools, self.v_pools)])
        else:
            data = jnp.stack(
                [jnp.stack((jnp.take(kp, ids, axis=0),
                            jnp.take(vp, ids, axis=0)))
                 for kp, vp in zip(self.k_pools, self.v_pools)])
            scales = None
        self._offload_pending_keys.add(key)
        self._offload_pending.append((key, data, scales))

    def _service_offload(self, tr):
        """Tick-boundary transfer drain: materialize the demote
        gathers the PREVIOUS tick's evictions enqueued and park them
        in the host store.  Runs right after ``_service_migrations``
        in both tick paths — by now the gathers have had a full
        dispatch of device time to complete, so ``np.asarray`` is a
        copy-out, not a stall (the double buffer)."""
        if self.host_store is None or not self._offload_pending:
            return
        pending = self._offload_pending
        self._offload_pending = []
        self._offload_pending_keys = set()
        store = self.host_store
        for key, data, scales in pending:
            with tr.span("offload.demote", cat="serving",
                         key=key) as sp:
                try:
                    d = np.asarray(data)[:, :, 0]
                    s = (np.asarray(scales)[:, :, 0]
                         if scales is not None else None)
                    ok = store.put(key, d, s)
                except Exception:
                    ok = False  # a dead gather (pools recovered
                    #   mid-flight) must not fail the tick
                if ok:
                    self._m_offload_demotes.inc()
                sp.args.update(stored=bool(ok))
        self._m_kv_host_blocks.set(len(store))
        self._m_kv_host_bytes.set(store.bytes_used)

    def _flush_offload(self):
        """Drain pending demotes at loop-idle boundaries
        (``run_until_idle`` exit, the ``start()`` loop's idle branch,
        ``_drain``) — an eviction in the last tick before idle must
        not strand its gather until the next burst of traffic."""
        try:
            self._service_offload(self.tracer)
        except Exception:
            self._offload_pending = []
            self._offload_pending_keys = set()

    def _promote_blocks(self, req, tokens, ctx, m, fresh):
        """Host-tier leg of paged admission: after the device trie
        matched ``m`` tokens, probe the host store for consecutive
        continuation blocks and restore them into the leading
        ``fresh`` reservations — import the payload, seed the device
        trie, and let ``_bind_kv_plan`` count the span exactly like a
        device prefix hit.  Returns the number of promoted blocks; 0
        on miss, scheduled ``offload_promote`` fault, or import
        failure — the fresh blocks then stay plain prefill targets
        (recompute), never half-restored."""
        store = self.host_store
        if store is None or not fresh:
            return 0
        from .offload import prefix_key
        bs = self._bs
        first = m // bs
        limit = (len(tokens) - 1) // bs  # leave >=1 token to prefill
        keys = []
        for i in range(first, min(limit, first + len(fresh))):
            key = prefix_key(tokens, (i + 1) * bs)
            if key not in store:  # presence probe: no LRU touch
                break
            keys.append(key)
        if not keys:
            return 0
        try:
            self._fault("offload_promote")
        except Exception:
            return 0  # scheduled promote failure: fall back to
            #   recompute — the store entry stays, untouched
        datas, scls = [], []
        for key in keys:
            ent = store.get(key)
            if ent is None:
                break  # demote-side LRU raced the probe
            datas.append(ent[0])
            scls.append(ent[1])
        n = len(datas)
        if not n:
            return 0
        blocks = fresh[:n]
        with self.tracer.span("offload.promote", cat="serving",
                              req=req.id, blocks=n) as sp:
            data = np.stack(datas, axis=2)
            scales = (np.stack(scls, axis=2)
                      if scls[0] is not None else None)
            try:
                self.k_pools, self.v_pools = import_blocks(
                    self.k_pools, self.v_pools, blocks, data, scales)
            except Exception:
                return 0  # pools untouched (import is all-or-nothing)
            self.prefix_cache.insert(tokens[:(first + n) * bs],
                                     ctx + blocks)
            sp.args.update(tokens=n * bs)
        self._m_offload_promotes.inc(n)
        self._m_offload_hit_tokens.inc(n * bs)
        req._host_restored = getattr(req, "_host_restored", 0) + n * bs
        self.tracer.instant("req.host_restored", cat="request",
                            req=req.id, blocks=n, tokens=n * bs)
        self._m_kv_host_blocks.set(len(store))
        self._m_kv_host_bytes.set(store.bytes_used)
        return n

    # -- tracing / flight recorder / debug surface ---------------------
    def _register_compile_listener(self):
        """Subscribe this engine to the model's compile events
        (idempotent).  ``stop()`` unsubscribes — a stopped engine must
        not keep counting sibling engines' compiles into its registry
        — and ``start()`` re-subscribes for the restart path; the
        weakref inside the callback still covers engines discarded
        without a stop()."""
        if self._compile_cb_active:
            return
        add = getattr(self.model, "add_compile_listener", None)
        if add is not None:
            add(self._compile_cb)
            self._compile_cb_active = True

    def _unregister_compile_listener(self):
        if not self._compile_cb_active:
            return
        remove = getattr(self.model, "remove_compile_listener", None)
        if remove is not None:
            remove(self._compile_cb)
        self._compile_cb_active = False

    def _on_compile(self, kind, key, wall_s):
        """Compile-event hook (models/gpt.py ``add_compile_listener``):
        count it, histogram the wall time, and back-date a trace span
        over the compile so it nests inside whatever engine phase
        triggered it."""
        self._m_compiles.inc()
        self._m_compile_ms.observe(wall_s * 1e3)
        # keep only the scalar fields of the program cache key — it
        # embeds the full parameter-name tuple, useless in a trace
        brief = ([x for x in key
                  if isinstance(x, (int, float, str, bool))]
                 if isinstance(key, tuple) else [str(key)])
        self.tracer.emit(
            f"compile:{kind}", time.perf_counter() - wall_s, wall_s,
            cat="compile",
            args={"key": brief, "wall_ms": round(wall_s * 1e3, 3)})

    def chrome_trace(self):
        """Current trace ring as a Catapult JSON dict (chrome://tracing
        / Perfetto); served by ``/debug/trace``."""
        return self.tracer.chrome_trace(
            process_name=f"paddle_tpu-serving pid={os.getpid()}")

    def streams_active(self):
        """Live TokenStream sinks across slot-bound + queued requests
        — the /healthz streaming-load signal (cheap: two locked
        snapshots, no device work)."""
        n = 0
        for s in self.scheduler.busy_slots():
            if s.request is not None:
                n += len(s.request._sinks)
        for r in self.queue.pending():
            n += len(r._sinks)
        return n

    def debug_requests(self):
        """In-flight slot/request states + queued requests as plain
        JSON-able dicts — the ``/debug/requests`` payload and the
        flight recorder's context block.  Readable from any thread
        while the engine decodes (one locked scheduler pass; the
        request fields it reads are single-writer ints)."""
        now = time.monotonic()
        # which un-consumed dispatch does each slot's DEVICE cursor
        # belong to?  (the newest in-flight tick containing the slot;
        # None = the host-consumed view is current)
        ring = list(self._ring)
        cursor_tick = {}
        for inf in ring:  # oldest -> newest, so the newest wins
            for s in inf.slots:
                cursor_tick[s.index] = inf.tick
        slots = []
        streams_active = 0
        for view in self.scheduler.debug_view():
            view["cursor_tick"] = cursor_tick.get(view["slot"])
            req = view.pop("request")
            if req is not None:
                view["request_id"] = req.id
                view["prompt_len"] = int(len(req.prompt))
                view["generated"] = len(req.generated)
                view["max_new_tokens"] = req.max_new_tokens
                view["do_sample"] = bool(req.do_sample)
                view["first_token"] = req.first_token_at is not None
                view["age_ms"] = round((now - req.submitted_at) * 1e3,
                                       3)
                view["preemptions"] = req.preemptions
                view["adapter"] = req.adapter
                view["streams"] = len(req._sinks)
                view["restored_from_host"] = getattr(
                    req, "_host_restored", 0)  # tokens whose prefill
                #   a host-tier promote skipped (0 = never restored)
                streams_active += len(req._sinks)
            if self._paged:
                view["kv_blocks"] = len(self._slot_blocks[view["slot"]])
            slots.append(view)
        queued = []
        for r in self.queue.pending():
            streams_active += len(r._sinks)
            queued.append({
                "request_id": r.id, "prompt_len": int(len(r.prompt)),
                "max_new_tokens": r.max_new_tokens,
                "priority": r.priority, "tenant": r.tenant,
                "preemptions": r.preemptions, "adapter": r.adapter,
                "queued_ms": round((now - r.submitted_at) * 1e3, 3),
                "deadline_in_s": (None if r.deadline is None
                                  else round(r.deadline - now, 3)),
            })
        return {
            "tick": self.tick_no, "slots": slots, "queue": queued,
            "streams_active": streams_active,
            "in_flight_ticks": [inf.tick for inf in ring],
            "preemptions": self._preempt_history()[-16:],
            "migrations": self._migration_history()[-16:],
            "migrations_pending": self._migrate_pending(),
            "offload": (None if self.host_store is None
                        else self.host_store.stats()),
            "engine": {
                "num_slots": self.num_slots,
                "max_seq_len": self.max_seq_len,
                "layout": "paged" if self._paged else "contiguous",
                "prefill_chunk": self._chunk,
                "spec_k": self._spec_k,
                "sample_mode": self.sample_mode,
                "attn_impl": self.attn_impl,
                "max_context_len": self._max_context_len,
                "mesh_shape": self.mesh_axes,
                "mp": self.mp,
                "dp": self.dp,
                "kv_block_bytes_per_shard":
                    self._kv_block_bytes_per_shard,
                "weight_dtype": self._weight_dtype_str,
                "kv_dtype": self._kv_dtype_str,
                "kv_block_bytes": self._kv_code_bytes_per_shard,
                "kv_scale_bytes": self._kv_scale_bytes_per_shard,
                "async_depth": self.async_depth,
                "tracing": bool(self.tracer.enabled),
                "preemption": self._preemption,
                "draining": self._draining,
                "watchdog_s": self.watchdog_s,
                "adapters_loaded": (0 if self.adapters is None
                                    else len(self.adapters)),
                "adapters": (None if self.adapters is None
                             else self.adapters.describe()),
            }}

    def _record_flight(self, exc):
        """Flight recorder: snapshot the trace ring + in-flight
        request states at the moment of a step failure, BEFORE
        recovery tears the slots down.  Always lands on
        ``self.last_flight``; additionally written to ``flight_dir``
        as chrome-trace JSON when configured.  Must never mask the
        real failure, so it swallows its own errors."""
        try:
            trace = self.chrome_trace()
            trace["metadata"] = {
                "flight-recorder": {
                    "error": repr(exc),
                    "tick": self.tick_no,
                    "dumped_at_unix": round(time.time(), 3),
                    "requests": self.debug_requests(),
                    # preemption/requeue history: WHY slots were
                    # evicted in the ticks leading up to the failure
                    "preemptions": self._preempt_history(),
                    # async pipeline state at the failure: BOTH cursor
                    # buffers — the host mirrors (the "next" buffer
                    # admissions/evictions dirty) and, per un-consumed
                    # in-flight tick, the buffer its dispatch chained
                    # from — plus the futures' metadata, all captured
                    # BEFORE recovery evicts and rebuilds
                    "async": {
                        "async_depth": self.async_depth,
                        "state_dirty": bool(self._state_dirty),
                        "in_flight": [inf.meta()
                                      for inf in list(self._ring)],
                        "next_buffer": {
                            "pos": self._pos.tolist(),
                            "cur_tok": self._cur_tok[:, 0].tolist(),
                            "rem": self._rem.tolist(),
                            "eos": self._eos.tolist(),
                            "ctr": self._sctr.tolist(),
                        },
                    },
                }}
            self.last_flight = trace
            if self._flight_dir:
                os.makedirs(self._flight_dir, exist_ok=True)
                path = os.path.join(
                    self._flight_dir,
                    f"flight_tick{self.tick_no}_{os.getpid()}_"
                    f"{int(time.time() * 1e3)}.json")
                with open(path, "w") as f:
                    json.dump(trace, f)
                self.last_flight_path = path
        except Exception:
            pass

    # -- paged KV cache (serving/kvcache.py) ---------------------------
    def _kv_gate(self, req, slot):
        """Paged admission gate — the scheduler consults it before
        binding a slot.  Matches the prompt against the prefix cache
        (adopting the shared span's blocks), then reserves every block
        the request could need UP FRONT, so decode never allocates and
        a running request can never die of pool pressure mid-stream.
        Under pressure, LRU-evicts unreferenced cached prefixes; if the
        pool still cannot cover the non-shared span, returns False and
        the request waits at the queue head.

        Data-parallel meshes: every lookup/eviction/reservation here
        is scoped to the BINDING SLOT's dp shard — the slot can only
        gather rows inside its own shard's pool range, so a prefix
        cached by another shard is invisible to it and the blocks
        must come from its own range.

        Speculative decoding widens the worst case by ``spec_k``: the
        verify window writes rejected-lane K/V up to spec_k positions
        past the cursor, and reserving those rows HERE is what makes
        rollback a cursor reset instead of a pool operation — every
        window position lands in blocks the slot already owns.

        Resume-aware: a preempted request's ``context`` is its frozen
        prompt+emitted snapshot and ``remaining`` its unemitted
        budget, so the worst case is the same total the original
        admission reserved — and the blocks the preemption returned
        to the prefix cache match here, which is what makes resume a
        cursor-and-refcount operation instead of a re-prefill."""
        tokens = req.context
        shard = self._slot_shard(slot.index)
        s = len(tokens)
        n_total = -(-(s + req.remaining + (self._spec_k or 0))
                    // self._bs)
        ctx, m = ([], 0)
        if self.prefix_cache is not None and not req._adapter_id:
            # adapter lanes never share cached K/V: LoRA on out_proj
            # shifts the residual stream, so layers >= 1 K/V depend
            # on the adapter — a base-lane prefix would be wrong
            ctx, m = self.prefix_cache.match(tokens, shard=shard)
        need = n_total - len(ctx)
        short = need - self.block_pool.free_count(shard)
        if short > 0 and self.prefix_cache is not None:
            evicted = self.prefix_cache.evict(short, shard=shard)
            if evicted:
                self._m_prefix_evictions.inc(len(evicted))
        if need > self.block_pool.free_count(shard):
            self.block_pool.decref(ctx)  # the cache keeps its own refs
            self._gate_declined = True   # preemption probe: the head
            #   is being held back by blocks, not by slots
            return False
        fresh = self.block_pool.alloc(need, shard=shard)
        if self.host_store is not None and not req._adapter_id:
            # second tier: the device trie answered first, the host
            # store restores the consecutive continuation (if any)
            # into the leading fresh blocks
            n_promo = self._promote_blocks(req, tokens, ctx, m, fresh)
            if n_promo:
                ctx = ctx + fresh[:n_promo]
                fresh = fresh[n_promo:]
                m += n_promo * self._bs
        req._kv_plan = (ctx, fresh, m)
        return True

    def _release_slot_kv(self, i):
        """Return slot i's block references (eviction path): cached
        prefix blocks fall back to the cache's reference and stay
        resident; decode-span blocks free."""
        if not self._paged:
            return
        self.block_pool.decref(self._slot_blocks[i])
        self._slot_blocks[i] = []
        self._block_tables[i, :] = self._slot_scratch[i]

    def _bind_kv_plan(self, slot):
        """Install the admission gate's block reservation
        (``req._kv_plan``) into the slot's table and count the prefix
        hit; returns (ctx, fresh, m).  Shared by the monolithic paged
        prefill and chunked admission."""
        req = slot.request
        ctx, fresh, m = req._kv_plan
        del req._kv_plan
        i = slot.index
        blocks = ctx + fresh
        self._slot_blocks[i] = blocks
        # scratch-padded tail: the pad is the slot's OWN dp shard's
        # scratch row (row 0 at dp == 1)
        row = np.full(self._bps, self._slot_scratch[i], np.int32)
        row[:len(blocks)] = blocks
        self._block_tables[i] = row
        if m:
            self._m_prefix_hits.inc()
            self._m_prefix_hit_tokens.inc(m)
            self.tracer.instant("req.prefix_adopted", cat="request",
                                req=req.id, tokens=m,
                                blocks=len(ctx))
        if self._kv_quant and fresh:
            self._zero_fresh_scales(fresh)
        return ctx, fresh, m

    def _zero_fresh_scales(self, fresh):
        """Zero the SCALE rows of freshly reserved quantized blocks
        (``kv_dtype='int8'``).  A recycled block's stale int8 codes
        would otherwise survive into the touched-block
        read-modify-write's amax recomputation (dequantized garbage
        raising the fresh block's scale); zeroing just the scale row
        nullifies them (``codes * 0 = 0``) without touching the code
        pool — unwritten rows then read exactly 0.0, masked by the
        same causal-position rule that hides fp stale garbage.  The
        index vector is padded to ``_bps`` by REPEATING the first
        fresh block (an idempotent re-zero that stays inside the
        reserving slot's own dp shard — a cross-shard pad row would
        be unaddressable once the tables go data-parallel), so ONE compiled
        program serves every admission regardless of reservation
        size — the no-retracing rule of the paged hot paths."""
        import jax
        import jax.numpy as jnp
        fn = self._zero_scale_fn
        if fn is None:
            def zero(k_pools, v_pools, idx):
                from .quant import QuantKV
                new_k, new_v = [], []
                for kp, vp in zip(k_pools, v_pools):
                    new_k.append(QuantKV(
                        kp.codes, kp.scale.at[idx].set(0.0)))
                    new_v.append(QuantKV(
                        vp.codes, vp.scale.at[idx].set(0.0)))
                return new_k, new_v

            fn = self._zero_scale_fn = jax.jit(
                zero, donate_argnums=(0, 1))
        pad = np.full(self._bps, fresh[0], np.int32)
        pad[:len(fresh)] = fresh
        self.k_pools, self.v_pools = fn(
            self.k_pools, self.v_pools, jnp.asarray(pad))

    def _dequant_span(self, tr, batch):
        """``decode.dequant``: the host-side attribution span of a
        QUANTIZED dispatch, nested inside ``decode.dispatch`` /
        ``decode.ragged``.  The per-block dequant itself runs FUSED
        inside the compiled program (codes x scale adjacent to the
        gather), so there is no separate host phase to time — this
        wraps the same dispatch call and records the worst-case code
        bytes the gather dequantizes (full tables), making quantized
        dispatches distinguishable in a trace (``tools/trace_view.py
        --wall`` breaks the span out).  fp engines emit nothing."""
        if not self._kv_quant:
            import contextlib
            return contextlib.nullcontext()
        return tr.span(
            "decode.dequant", cat="serving", batch=batch,
            code_bytes=batch * self._bps
            * (self._kv_code_bytes_per_shard or 0))

    # -- per-slot sampling lanes (sample_mode="device") ----------------
    def _bind_sample_state(self, slot):
        """Install the admitted request's sampling lane into the state
        mirrors (admission): temperature 0 marks a greedy lane, the
        seed words feed the on-device key derivation, and the rng
        counter restarts at 0 — so two engines given the same seed
        emit the same sampled tokens.  Dirtying the mirrors makes the
        next device-mode tick re-upload them (host mode ships state
        every tick anyway and ignores the lanes).

        A GREEDY request's lane binds CONSTANT zero seed words, not
        its id-derived default seed: its draw is discarded (argmax),
        but under the rbg PRNG — this repo's TPU-native default — a
        vmapped categorical's bits depend on the WHOLE key batch, so
        an unstable junk key (request ids are a process-global
        counter) would perturb the *seeded neighbors'* streams and
        break their reproduce-across-restarts contract whenever a
        greedy request shared the batch."""
        req = slot.request
        i = slot.index
        if req.do_sample:
            self._temp[i] = req.temperature
            self._topk[i] = req.top_k
            self._topp[i] = req.top_p
            lo, hi = req.seed_words()
        else:
            self._temp[i] = 0.0
            self._topk[i] = 0
            self._topp[i] = 1.0
            lo, hi = 0, 0
        self._seed_lo[i] = lo
        self._seed_hi[i] = hi
        # rng fold counter = tokens already emitted: 0 on a fresh
        # admission, len(generated) on a preemption resume — so the
        # next device draw is draw #len(generated) either way and a
        # seeded stream is unchanged across a preemption
        self._sctr[i] = len(req.generated)
        # device-side stop-condition lanes: the dispatch itself checks
        # EOS / max_new against these, so a blind-dispatched tick can
        # never advance a finished request (resume: only the unemitted
        # budget remains)
        self._eos[i] = (-1 if req.eos_token_id is None
                        else int(req.eos_token_id))
        self._rem[i] = req.remaining
        # LoRA lane: which adapter this slot decodes through (0 =
        # base).  Data like everything else here — never a retrace.
        self._aid[i] = req._adapter_id
        self._state_dirty = True

    def _park_state(self, i):
        """Park slot i's step + sampling lanes (eviction): frozen
        zeros keep the inactive row's (discarded) compute in-bounds
        and greedy-cheap until the next admission overwrites them; the
        dirty flag makes the next device-mode tick re-upload the
        corrected cursors — a mid-window eviction may have advanced
        the device cursor further than the host consumed."""
        self._pos[i] = 0
        self._cur_tok[i, 0] = 0
        self._temp[i] = 0.0
        self._topk[i] = 0
        self._topp[i] = 1.0
        self._seed_lo[i] = 0
        self._seed_hi[i] = 0
        self._sctr[i] = 0
        self._eos[i] = -1
        self._rem[i] = 0  # rem 0 = the device freezes this lane
        self._aid[i] = 0  # parked compute runs the base lane (zeros)
        self._state_dirty = True

    def _push_state(self):
        """Upload the state mirrors as the device-resident step state
        (device mode): runs only when an admission / eviction / chunk
        dirtied them — a steady-state tick reuses the handles the last
        dispatch returned and uploads NOTHING.  The pipeline must be
        drained first: the mirrors only reflect CONSUMED ticks, so
        uploading them under an un-consumed dispatch would rewind
        every other slot's device cursor by a tick."""
        assert not self._ring, \
            "_push_state with ticks in flight — drain the ring first"
        import jax.numpy as jnp
        # transfer from PRIVATE COPIES: the PJRT CPU client may run
        # the host->device copy asynchronously, so handing it the live
        # mirror races any mirror write that lands before the enqueued
        # dispatch executes — concretely, the ragged chunk lanes
        # advance self._pos right after dispatch, and the in-flight
        # transfer would intermittently capture the POST-chunk cursor
        # as the pre-state (observed as nondeterministic corruption)
        if self._repl_sharding is not None:
            # mesh-sharded engine: every [num_slots]-leading cursor
            # row-shards over 'dp' (each dp shard owns ITS slots'
            # cursors and block-table rows; at dp == 1 the spec
            # degenerates to replication over 'mp') — an uncommitted
            # single-device upload would make the first dispatch
            # re-shard them.  The placement is a cross-shard barrier,
            # traced as shard.sync so its cost is visible in
            # trace_view --wall
            import jax
            state_sh = self._state_sharding or self._repl_sharding

            def put(a):
                return jax.device_put(a.copy(), state_sh)
            sync = (self.tracer.span("shard.sync",
                                     shards=self.mp * self.dp,
                                     mp=self.mp, dp=self.dp)
                    if self.mp * self.dp > 1 else nullcontext())
        else:
            def put(a):
                return jnp.asarray(a.copy())
            sync = nullcontext()
        with sync:
            self._dev_state = dict(
                tok=put(self._cur_tok), pos=put(self._pos),
                ctr=put(self._sctr), temp=put(self._temp),
                topk=put(self._topk), topp=put(self._topp),
                slo=put(self._seed_lo), shi=put(self._seed_hi),
                eos=put(self._eos), rem=put(self._rem))
            if self.adapters is not None:
                self._dev_state["aid"] = put(self._aid)
            if self._paged:
                self._dev_state["tables"] = put(self._block_tables)
                # per-slot scratch block ids (constant per engine
                # config, but rides the state dict so the ragged
                # dispatch signature stays uniform): masked/parked
                # lanes park in their OWN dp shard's scratch row
                self._dev_state["scratch"] = put(self._slot_scratch)
        self._state_dirty = False

    def _prefill_paged(self, slot):
        """Paged admission prefill: ONE jitted dispatch gathers the
        adopted prefix blocks as attention context, runs the prompt's
        non-shared tail, and scatters the tail's K/V block-granular
        into the slot's fresh blocks — a prefix hit neither recomputes
        nor re-stores the shared span.  The prompt's full blocks are
        then registered in the prefix cache for later adopters."""
        import jax.numpy as jnp
        req = slot.request
        ctx, fresh, m = self._bind_kv_plan(slot)
        i = slot.index
        blocks = ctx + fresh
        tokens = req.context  # prompt, or the frozen resume snapshot
        s = len(tokens)
        n_ctx = len(ctx)
        s_tail = s - m
        n_tail = -(-s // self._bs) - n_ctx
        pf, _, _ = self.model._compiled_paged_prefill_fn(
            self._pnames, self._params,
            self._lora_key(
                (s_tail, n_ctx, n_tail, self._bs, self._kv_dtype_str,
                 tuple(self._pnames), self._bnames_all)),
            s_tail, n_ctx, n_tail, self._bs, self._nh, self._hd,
            self._kv_dtype)
        last0, self.k_pools, self.v_pools = pf(
            self._p_list(), self._b_list(), self.k_pools, self.v_pools,
            tokens[None, m:],
            jnp.asarray(np.asarray(ctx, np.int32)),
            jnp.asarray(np.asarray(fresh[:n_tail], np.int32)),
            *self._lora_args_slot(req))
        if self.prefix_cache is not None and not req._adapter_id:
            self.prefix_cache.insert(tokens, blocks[:s // self._bs])
        self._m_prefill_tokens.inc(s_tail)
        slot.pos = s
        slot.prefilled = s
        self._pos[i] = s
        tok = self._pick(req, np.asarray(last0, np.float32)[0])
        self._emit(slot, tok)

    def _prefill(self, slot):
        """Admission prefill: one jitted whole-prompt forward (shared
        with ``generate(compiled=...)`` via _compiled_prefill_fn, so the
        math is the compiled path's bit-for-bit; or the bucketed
        right-padded variant when prefill_buckets bounds compiles),
        padded to the pool's L and written into the slot's cache rows."""
        import jax.numpy as jnp
        self._bind_sample_state(slot)
        if self._paged:
            return self._prefill_paged(slot)
        req = slot.request
        tokens = req.context  # prompt, or the frozen resume snapshot
        s = len(tokens)
        L = self.max_seq_len
        if self._prefill_buckets is not None:
            S = next(b for b in self._prefill_buckets if b >= s)
            pf, _, _ = self.model._compiled_bucket_prefill_fn(
                self._pnames, self._params,
                self._lora_key(
                    (1, S, L, self._kv_dtype_str, tuple(self._pnames),
                     self._bnames_all)),
                1, S, L, self._nh, self._hd, self._kv_dtype)
            ids = np.zeros((1, S), np.int32)
            ids[0, :s] = tokens
            last0, k_bufs, v_bufs = pf(self._p_list(), self._b_list(),
                                       ids, jnp.asarray(s, jnp.int32),
                                       *self._lora_args_slot(req))
        else:
            pf, _, _ = self.model._compiled_prefill_fn(
                self._pnames, self._params,
                self._lora_key(
                    (1, s, L, self._kv_dtype_str, tuple(self._pnames),
                     self._bnames_all)),
                1, s, L, self._nh, self._hd, self._kv_dtype)
            last0, k_bufs, v_bufs = pf(self._p_list(), self._b_list(),
                                       tokens[None, :],
                                       *self._lora_args_slot(req))
        i = slot.index
        if self._insert_fn is None:
            import jax

            def ins(k_pools, v_pools, k_news, v_news, idx):
                # one dispatch writes the slot row into every layer;
                # donated pools update in place instead of 2*n_layers
                # whole-pool copies per admission
                new_k = [jax.lax.dynamic_update_slice(
                    kp, kn.astype(kp.dtype), (idx, 0, 0, 0))
                    for kp, kn in zip(k_pools, k_news)]
                new_v = [jax.lax.dynamic_update_slice(
                    vp, vn.astype(vp.dtype), (idx, 0, 0, 0))
                    for vp, vn in zip(v_pools, v_news)]
                return new_k, new_v

            self._insert_fn = jax.jit(ins, donate_argnums=(0, 1))
        import jax.numpy as jnp
        self.k_pools, self.v_pools = self._insert_fn(
            self.k_pools, self.v_pools, k_bufs, v_bufs,
            jnp.asarray(i, jnp.int32))
        self._m_prefill_tokens.inc(s)
        slot.pos = s
        slot.prefilled = s
        self._pos[i] = s
        tok = self._pick(req, np.asarray(last0, np.float32)[0])
        self._emit(slot, tok)

    # -- budgeted chunked prefill (prefill_chunk=...) ------------------
    def _begin_chunked(self, slot):
        """Chunked admission: bind the paged block plan (the adopted
        prefix span counts as already-prefilled tokens) and park the
        slot PREFILLING — no prompt compute happens at admission;
        ``_prefill_chunked`` spends the tick budget.  The decode
        dispatch's (discarded) compute for a half-prefilled slot is
        parked at the NEXT chunk's start row: its garbage K/V write
        lands on a row that chunk overwrites before any query can see
        it (in paged mode that row always sits in the slot's own fresh
        blocks — the adopted shared blocks all lie before
        ``prefilled``)."""
        i = slot.index
        self._bind_sample_state(slot)
        if self._paged:
            _, _, m = self._bind_kv_plan(slot)
            slot.prefilled = m
        else:
            slot.prefilled = 0
        slot.pos = slot.prefilled
        self._pos[i] = slot.prefilled
        self._cur_tok[i, 0] = 0

    def _run_chunk(self, slot, n):
        """One chunk dispatch: compute K/V (and, on the final chunk,
        the first-token logits) for prompt positions
        ``[prefilled, prefilled + n)``.  Returns 1 when the final chunk
        emitted the request's first token, else 0."""
        import jax.numpy as jnp
        req = slot.request
        i = slot.index
        tokens = req.context  # prompt, or the frozen resume snapshot
        s = len(tokens)
        p0 = slot.prefilled
        C = self._chunk
        ids = np.zeros((1, C), np.int32)  # right-padded final chunk
        ids[0, :n] = tokens[p0:p0 + n]
        with self.tracer.span(
                "prefill.chunk", req=req.id, pos=p0, n=n,
                layout="paged" if self._paged else "contiguous"):
            if self._paged:
                fn, _, _ = self.model._compiled_paged_chunk_prefill_fn(
                    self._pnames, self._params,
                    self._lora_key(
                        (C, self._kv_managed + self.dp, self._bs, self._bps,
                         self._kv_dtype_str, tuple(self._pnames),
                         self._bnames_all)))
                last0, self.k_pools, self.v_pools = fn(
                    self._p_list(), self._b_list(), self.k_pools,
                    self.v_pools, ids,
                    jnp.asarray(self._block_tables[i]),
                    jnp.asarray(p0, jnp.int32),
                    jnp.asarray(n, jnp.int32),
                    jnp.asarray(int(self._slot_scratch[i]), jnp.int32),
                    *self._lora_args_slot(req))
            else:
                fn, _, _ = self.model._compiled_chunk_prefill_fn(
                    self._pnames, self._params,
                    self._lora_key(
                        (C, self.num_slots, self.max_seq_len,
                         self._kv_dtype_str, tuple(self._pnames),
                         self._bnames_all)),
                    C, self.max_seq_len, self._nh, self._hd,
                    self._kv_dtype)
                last0, self.k_pools, self.v_pools = fn(
                    self._p_list(), self._b_list(), self.k_pools,
                    self.v_pools, ids, jnp.asarray(i, jnp.int32),
                    jnp.asarray(p0, jnp.int32),
                    jnp.asarray(n, jnp.int32),
                    *self._lora_args_slot(req))
        slot.prefilled = p0 + n
        slot.pos = slot.prefilled
        self._m_chunks.inc()
        self._m_prefill_tokens.inc(n)
        self._state_dirty = True  # device-mode cursors must re-park on
        #   the chunk's new start row before the next fused tick
        if slot.prefilled < s:
            # still PREFILLING: re-park the decode dispatch's garbage
            # write on the next chunk's start row
            self._pos[i] = slot.prefilled
            return 0
        # final chunk: the context's full blocks become adoptable and
        # the last real position's logits sample the first token (TTFT
        # on a fresh admission; the NEXT stream token on a resume)
        if self._paged and self.prefix_cache is not None \
                and not req._adapter_id:
            self.prefix_cache.insert(tokens,
                                     self._slot_blocks[i][:s // self._bs])
        self._pos[i] = s
        tok = self._pick(req, np.asarray(last0, np.float32)[0])
        self._emit(slot, tok)
        return 1

    def _prefill_chunked(self, prefilling):
        """Spend at most ``tick_token_budget`` prompt tokens on prefill
        chunks: round-robin over the PREFILLING slots (admission order,
        so partially-prefilled prompts resume before fresh ones start),
        one chunk per slot per pass.  Returns (tokens_emitted,
        newly_decoding_slots, evicted_count) — newly-decoding slots
        join this same tick's decode dispatch, exactly like monolithic
        prefill's emit-then-decode."""
        from collections import deque
        budget = self._tick_budget
        emitted, newly, evicted = 0, [], 0
        queue = deque(prefilling)
        while queue and budget > 0:
            slot = queue.popleft()
            req = slot.request
            n = min(self._chunk, len(req.context) - slot.prefilled)
            if n > budget:
                break  # strict per-tick cap (budget >= chunk, so a
                #        tick's FIRST chunk always fits: progress is
                #        guaranteed, the cap only defers later chunks)
            done_first = self._run_chunk(slot, n)
            budget -= n
            if done_first:
                emitted += 1
                if slot.request is not None:
                    newly.append(slot)
                else:
                    evicted += 1  # EOS / max_new_tokens on first token
            else:
                queue.append(slot)
        return emitted, newly, evicted

    def _pick(self, req, row):
        """Next token from one slot's f32 logits row: argmax (greedy —
        identical in both sample modes), device-twin filtered sampling
        (sample_mode="device"), or filtered numpy sampling on a
        per-request rng stream (host mode's legacy numerics)."""
        if not req.do_sample:
            return int(np.argmax(row))
        if self.sample_mode == "device":
            return self._pick_device(req, row)
        rng = self._rngs.get(req.id)
        if rng is None:
            rng = self._rngs[req.id] = np.random.default_rng(
                req.sample_seed)
        filt = _filter_logits_np(row, req.temperature, req.top_k,
                                 req.top_p)
        return int(rng.choice(len(filt), p=_softmax_np(filt)))

    def _pick_device(self, req, row):
        """Device-mode first-token pick (prefill / final chunk): the
        SAME lane filters and key derivation as the fused dispatches
        (``models.gpt.sample_rows`` — one process-wide compile), run
        on the one [V] logits row prefill already returned — so token
        i of a request draws from fold(request_key, i) whether
        prefill, a one-token tick, or a verify-window lane emitted it,
        and a seed reproduces across engine restarts."""
        import jax.numpy as jnp
        from ..models.gpt import sample_rows
        lo, hi = req.seed_words()
        ids = sample_rows(
            jnp.asarray(row, jnp.float32)[None, :],
            jnp.asarray([req.temperature], jnp.float32),
            jnp.asarray([req.top_k], jnp.int32),
            jnp.asarray([req.top_p], jnp.float32),
            jnp.asarray([lo], jnp.uint32), jnp.asarray([hi], jnp.uint32),
            jnp.asarray([len(req.generated)], jnp.int32))
        return int(np.asarray(ids)[0])

    def _emit(self, slot, tok):
        """Record one generated token; finish + evict on EOS or
        max_new_tokens, else arm the slot for the next tick."""
        req = slot.request
        now = time.monotonic()
        if req._sinks:
            # live streaming consumers: fan the token out under the
            # sink lock (exactly-once vs a concurrent attach replay);
            # spanned so trace_view --wall prices the fan-out
            with self.tracer.span("stream.emit", cat="serving",
                                  req=req.id):
                req._emit_token(int(tok))
        else:
            req._emit_token(int(tok))
        if req.first_token_at is None:
            req.first_token_at = now
            self._m_ttft.observe((now - req.submitted_at) * 1e3)
            self.tracer.instant(
                "req.first_token", cat="request", req=req.id,
                ttft_ms=round((now - req.submitted_at) * 1e3, 3))
        self._m_tokens.inc()
        self._m_rate.add(1, now)
        # context high-water mark: prompt + everything decoded so far
        # — the max context length this engine has actually served
        # (reported in /healthz + /debug/requests, copied into the
        # router's probe signals)
        ctx_len = len(req.prompt) + len(req.generated)
        if ctx_len > self._max_context_len:
            self._max_context_len = ctx_len
        finished = (len(req.generated) >= req.max_new_tokens or
                    (req.eos_token_id is not None
                     and int(tok) == int(req.eos_token_id)))
        if finished:
            n_after_first = len(req.generated) - 1
            if n_after_first > 0:
                self._m_tpot.observe(
                    (now - req.first_token_at) / n_after_first * 1e3)
            self._rngs.pop(req.id, None)
            i = slot.index
            self.scheduler.evict(slot)
            self._evicted_in_tick += 1
            self._release_slot_kv(i)
            # park the freed row (frozen pos/tok keeps the inactive
            # row's ignored compute in-bounds until the next prefill
            # overwrites the whole cache row) and dirty the device
            # mirrors
            self._park_state(i)
            self._m_done.inc()
            self.tracer.instant("req.finished", cat="request",
                                req=req.id,
                                tokens=len(req.generated))
            return
        i = slot.index
        self._cur_tok[i, 0] = int(tok)
        self._pos[i] = slot.pos
        self._sctr[i] = len(req.generated)  # rng fold counter mirror
        self._rem[i] = req.max_new_tokens - len(req.generated)
        #   remaining-budget mirror: tracks the device lane exactly
        #   (both decrement once per emitted token), so steady state
        #   needs no re-upload

    def _draft_window(self, active):
        """Gather the speculative verify window: [num_slots, W] tokens
        whose lane 0 is each slot's current token and lanes 1..k are
        the proposer's drafts (pad lanes repeat the current token).
        Sets ``slot.spec_lanes`` per live slot.  Shared by the host
        verify tick and the fused device tick."""
        k = self._spec_k
        W = k + 1
        toks = np.zeros((self.num_slots, W), np.int32)
        toks[:, 0] = self._cur_tok[:, 0]
        for slot in active:
            i = slot.index
            req = slot.request
            # clamp to what the request can still consume: the window
            # emits at most (lanes + 1) tokens before max_new_tokens
            # evicts, so lanes past remaining-1 could never be
            # accepted — proposing them would waste proposer work and
            # permanently deflate the acceptance-rate gauge with
            # request-length effects that say nothing about draft
            # quality (the compiled window shape stays the full W;
            # the tail just rides as pad lanes)
            n_lanes = min(k, req.max_new_tokens - len(req.generated) - 1)
            toks[i, 1:] = toks[i, 0]  # pad lanes: repeat the current
            #   token — window FILLER, never proposals (their garbage
            #   K/V is rewritten before visibility like any rejected
            #   lane, and the accept loop below cannot consume them)
            n_drafted = 0
            if n_lanes > 0:
                history = np.concatenate(
                    [req.prompt, np.asarray(req.generated, np.int32)])
                try:
                    self._fault("spec_draft")
                    d = np.asarray(
                        self.proposer.propose(history, n_lanes),
                        np.int32).reshape(-1)[:n_lanes]
                except Exception as e:
                    # a proposer outage DEGRADES (zero drafts — the
                    # verify window still emits its bonus token, i.e.
                    # plain decode speed) instead of failing the tick
                    # and evicting every in-flight request
                    self._m_proposer_failures.inc()
                    self.tracer.instant(
                        "spec.proposer_failed", cat="serving",
                        req=req.id, error=repr(e))
                    d = np.zeros(0, np.int32)
                toks[i, 1:1 + len(d)] = d
                n_drafted = len(d)
            slot.spec_lanes = n_drafted  # in-flight REAL draft lanes —
            #   what the proposer returned, not what was asked: a
            #   shortfall's pad fill must not count as proposed nor be
            #   consumable as accepted.  (Counted into the proposed
            #   metric only after the dispatch returns: a failed
            #   verify must not deflate the lifetime acceptance-rate
            #   gauge with lanes never scored.)
        return toks

    def _spec_decode_tick(self, active):
        """One speculative DRAFT-AND-VERIFY dispatch (spec_k=..., host
        sampling): gather k draft tokens per live slot from the
        proposer, score all k+1 window positions in one jitted verify
        dispatch, then per slot emit the longest prefix where the
        target's pick equals the draft plus the one bonus token —
        1..k+1 tokens per slot per dispatch.  The write cursor
        advances only over emitted tokens; rejected lanes leave
        garbage K/V that the next window (which always spans the full
        k+1 positions from the new cursor) rewrites before any query
        can see it."""
        import jax.numpy as jnp
        tr = self.tracer
        W = self._spec_k + 1
        layout = "paged" if self._paged else "contiguous"
        with tr.span("spec.draft", batch=len(active), spec_k=W - 1):
            toks = self._draft_window(active)
        if self._spec_fn is None:
            self._spec_fn, _, _ = self.model._compiled_spec_verify_fn(
                self._pnames, self._params,
                ("paged" if self._paged else "slot", W, self.num_slots,
                 (self._kv_managed + self.dp, self._bs) if self._paged
                 else self.max_seq_len, self._kv_dtype_str,
                 tuple(self._pnames), self._bnames_all),
                paged=self._paged)
        fn = self._spec_fn
        self._fault("dispatch")
        with tr.span("decode.dispatch", batch=len(active),
                     layout=layout, spec_w=W):
            if self._paged:
                last, self.k_pools, self.v_pools = fn(
                    self._p_list(), self._b_list(), self.k_pools,
                    self.v_pools, jnp.asarray(self._block_tables),
                    jnp.asarray(toks), jnp.asarray(self._pos))
            else:
                last, self.k_pools, self.v_pools = fn(
                    self._p_list(), self._b_list(), self.k_pools,
                    self.v_pools, jnp.asarray(toks),
                    jnp.asarray(self._pos))
        with tr.span("decode.d2h") as d2h_sp:
            rows = np.asarray(last, np.float32)       # [B, W, V]
            d2h_sp.args["bytes"] = rows.nbytes
        self._m_d2h.set(rows.nbytes)
        self._m_spec_windows.inc(len(active))
        t_sample = time.monotonic()
        emitted = 0
        total_acc = 0
        # `with`, not manual enter/exit: a _pick/_emit failure mid-loop
        # must still record this span — it is exactly the phase the
        # flight-recorder dump needs to show
        with tr.span("decode.sample", batch=len(active),
                     layout=layout) as sample_sp:
            for slot in active:
                i = slot.index
                req = slot.request
                self._m_spec_proposed.inc(slot.spec_lanes)
                n_emit = 0
                n_acc = 0
                j = 0
                while True:
                    # lane j's logits are conditioned on exactly the
                    # accepted tokens, so _pick here equals the
                    # one-token tick's _pick for the same prefix
                    # (greedy AND seeded sampling: one rng draw per
                    # emitted token either way)
                    tok = self._pick(req, rows[i, j])
                    # only REAL lanes can match: a pad lane that
                    # happens to equal the pick must not be consumed
                    # (eviction at max_new would stop it anyway — this
                    # makes the bound local instead of an
                    # invariant-at-a-distance)
                    matched = j < slot.spec_lanes \
                        and int(toks[i, j + 1]) == tok
                    if matched:
                        # counted even when this very token finishes
                        # the request (EOS proposed by a matched
                        # lane): the draft DID predict an emitted
                        # token, and n_emit - 1 would silently
                        # undercount it
                        n_acc += 1
                    slot.pos += 1
                    self._pos[i] = slot.pos
                    self._emit(slot, tok)
                    n_emit += 1
                    if slot.request is None or not matched:
                        break  # finished/evicted, or first mismatch
                    j += 1     # draft j verified: consume lane j+1
                slot.spec_lanes = 0
                self._m_spec_accepted.inc(n_acc)
                total_acc += n_acc
                emitted += n_emit
            sample_sp.args.update(emitted=emitted, accepted=total_acc)
        self._m_sample_ms.observe((time.monotonic() - t_sample) * 1e3)
        proposed = self._m_spec_proposed.value
        if proposed:
            self._m_spec_rate.set(
                self._m_spec_accepted.value / proposed)
        self._m_spec_tpt.set(emitted / len(active))
        return emitted

    def _dispatch_spec(self, active, tr):
        """DISPATCH one fused speculative draft-and-verify tick
        without consuming it: the verify dispatch picks every window
        lane's token on device, counts the accepted prefix, AND
        applies the device-side stop condition (EOS / remaining
        budget clamp the emitted window and freeze finished lanes),
        so the un-materialized handles carry picks [B, W] + counts +
        the packed done mask — never the [B, W, V] logits.  Drafting
        stays host-side and data-dependent on the PREVIOUS window's
        accepted tokens, which is why the async loop consumes before
        drafting in spec mode."""
        import jax.numpy as jnp
        W = self._spec_k + 1
        layout = "paged" if self._paged else "contiguous"
        with tr.span("spec.draft", batch=len(active), spec_k=W - 1):
            toks = self._draft_window(active)
        lanes = np.zeros(self.num_slots, np.int32)
        for slot in active:
            lanes[slot.index] = slot.spec_lanes
        if self._state_dirty or self._dev_state is None:
            self._push_state()
        st = self._dev_state
        if self._fused_spec_fn is None:
            self._fused_spec_fn, _, _ = \
                self.model._compiled_fused_spec_verify_fn(
                    self._pnames, self._params,
                    self._lora_key(
                        ("paged" if self._paged else "slot", W,
                         self.num_slots,
                         (self._kv_managed + self.dp, self._bs) if self._paged
                         else self.max_seq_len, self._kv_dtype_str,
                         tuple(self._pnames), self._bnames_all)),
                    paged=self._paged)
        args = [self._p_list(), self._b_list(), self.k_pools,
                self.v_pools]
        if self._paged:
            args.append(st["tables"])
        args += [jnp.asarray(toks), jnp.asarray(lanes), st["pos"],
                 st["temp"], st["topk"], st["topp"], st["slo"],
                 st["shi"], st["ctr"], st["eos"], st["rem"],
                 *self._lora_args_state(st)]
        self._fault("dispatch")
        with tr.span("decode.dispatch", batch=len(active),
                     layout=layout, spec_w=W, fused=True), \
                self._dequant_span(tr, len(active)):
            (picks, n_acc, n_emit, done, new_tok, new_pos, new_ctr,
             new_rem, self.k_pools, self.v_pools) = \
                self._fused_spec_fn(*args)
        st["tok"], st["pos"], st["ctr"], st["rem"] = \
            new_tok, new_pos, new_ctr, new_rem
        self._m_fused_ticks.inc()
        self._m_spec_windows.inc(len(active))
        return _InflightTick(
            self.tick_no, "spec", list(active),
            {"picks": picks, "n_acc": n_acc, "n_emit": n_emit,
             "done": done}, len(active), layout,
            {"pos": self._pos.tolist(), "rem": self._rem.tolist()},
            spec_lanes=[slot.spec_lanes for slot in active])

    def _emit_window_lane(self, slot, picks_row, acc_i, n_emit_dev_i,
                          done_i, tick):
        """Shared per-slot emit loop of the windowed consume paths
        (``_consume_spec`` and ``_consume_ragged``'s mode-0 lanes):
        consume the device-accepted lanes plus the bonus token,
        advancing pos/mirrors through ``_emit``.  Lane j's pick was
        drawn on device from the same key/logits the one-token tick
        would use for this prefix, and ``acc_i`` counts only REAL
        draft lanes, so consuming lanes 0..acc_i reproduces the host
        accept loop exactly; an accepted lane is counted even when
        its token finishes the request (EOS drafted by a matched
        lane), but only over lanes actually consumed.  Host-vs-device
        stop-condition drift raises into step recovery — ONE
        implementation, so the two consume paths' drift semantics
        cannot desynchronize.  Returns (emitted, accepted)."""
        i = slot.index
        n_cnt = 0
        n_em = 0
        j = 0
        while True:
            tok = int(picks_row[j])
            matched = j < acc_i
            if matched:
                n_cnt += 1
            slot.pos += 1
            self._pos[i] = slot.pos
            self._emit(slot, tok)
            n_em += 1
            if slot.request is None or not matched:
                break
            j += 1
        slot.spec_lanes = 0
        if n_em != n_emit_dev_i or done_i != (slot.request is None):
            raise RuntimeError(
                f"async stop-condition drift: slot {i} host "
                f"emitted {n_em} (finished={slot.request is None}) "
                f"vs device n_emit={n_emit_dev_i} done={done_i} "
                f"at tick {tick}")
        return n_em, n_cnt

    def _consume_spec(self, inf, mats, done, tr):
        """Emit a materialized speculative tick: consume exactly the
        device-accepted lanes per slot (plus the bonus token), with
        the same acceptance accounting as the host verify loop.  The
        device-computed emitted-window length (``n_emit``) must match
        what the host loop consumed — a mismatch means the on-device
        stop condition diverged from ``_emit`` and raises into the
        step-failure recovery path."""
        picks = mats["picks"]
        n_acc = mats["n_acc"]
        n_emit_dev = mats["n_emit"]
        emitted = 0
        total_acc = 0
        # `with`, not manual enter/exit: an _emit failure mid-loop must
        # still record the span for the flight-recorder dump
        with tr.span("decode.emit", batch=inf.batch,
                     layout=inf.layout) as emit_sp:
            for slot, req, lanes_i in zip(inf.slots, inf.reqs,
                                          inf.spec_lanes):
                i = slot.index
                if slot.request is not req:
                    if not done[i]:
                        raise RuntimeError(
                            f"async stop-condition drift: slot {i} "
                            f"was evicted on the host but tick "
                            f"{inf.tick}'s device lane is not done")
                    continue
                self._m_spec_proposed.inc(lanes_i)
                n_em, n_cnt = self._emit_window_lane(
                    slot, picks[i], int(n_acc[i]),
                    int(n_emit_dev[i]), bool(done[i]), inf.tick)
                self._m_spec_accepted.inc(n_cnt)
                total_acc += n_cnt
                emitted += n_em
            emit_sp.args.update(emitted=emitted, accepted=total_acc)
        proposed = self._m_spec_proposed.value
        if proposed:
            self._m_spec_rate.set(
                self._m_spec_accepted.value / proposed)
        self._m_spec_tpt.set(emitted / inf.batch)
        return emitted

    def _fused_spec_tick(self, active):
        """Synchronous fused speculative tick (async_depth=1 path):
        dispatch + immediate consume — today's tick shape."""
        inf = self._dispatch_spec(active, self.tracer)
        return self._consume(inf, self.tracer)

    def _dispatch_decode(self, active, tr):
        """DISPATCH one fused decode+sample tick (sample_mode=
        "device") without consuming it: the step state lives on
        device between ticks (re-uploaded only when admissions /
        evictions / chunks dirtied the mirrors — which requires an
        empty pipeline, see ``_push_state``), sampling AND the stop
        condition run inside the dispatch, and the returned
        ``_InflightTick`` holds the un-materialized [B] ids + packed
        done-mask handles — jax async dispatch means this returns as
        soon as the program is enqueued, so the host can plan the
        next tick (or emit the previous one) while the device
        computes."""
        if self._state_dirty or self._dev_state is None:
            self._push_state()
        st = self._dev_state
        if self._fused_fn is None:
            self._fused_fn, _, _ = self.model._compiled_fused_decode_fn(
                self._pnames, self._params,
                self._lora_key(
                    ("paged" if self._paged else "slot", self.num_slots,
                     (self._kv_managed + self.dp, self._bs) if self._paged
                     else self.max_seq_len, self._kv_dtype_str,
                     tuple(self._pnames), self._bnames_all)),
                paged=self._paged)
        args = [self._p_list(), self._b_list(), self.k_pools,
                self.v_pools]
        if self._paged:
            args.append(st["tables"])
        args += [st["tok"], st["pos"], st["temp"], st["topk"],
                 st["topp"], st["slo"], st["shi"], st["ctr"],
                 st["eos"], st["rem"], *self._lora_args_state(st)]
        layout = "paged" if self._paged else "contiguous"
        self._fault("dispatch")
        with tr.span("decode.dispatch", batch=len(active),
                     layout=layout, fused=True), \
                self._dequant_span(tr, len(active)):
            (ids, done, new_tok, new_pos, new_ctr, new_rem,
             self.k_pools, self.v_pools) = self._fused_fn(*args)
        st["tok"], st["pos"], st["ctr"], st["rem"] = \
            new_tok, new_pos, new_ctr, new_rem
        self._m_fused_ticks.inc()
        return _InflightTick(
            self.tick_no, "decode", list(active),
            {"ids": ids, "done": done}, len(active), layout,
            {"pos": self._pos.tolist(), "rem": self._rem.tolist()})

    def _consume_decode(self, inf, mats, done, tr):
        """Emit a materialized decode tick's tokens (the consume
        side: pure host work on already-downloaded arrays, so at
        async_depth > 1 it runs while the NEXT tick computes).  Lanes
        whose request was evicted by an earlier tick's consume are
        skipped via the ``slot.request is req`` identity check — the
        device froze them (done bit), and the slot may already carry
        a new request.  Host-vs-device stop-condition drift raises,
        turning a would-be silent corruption into a recovered step
        failure."""
        ids = mats["ids"]
        emitted = 0
        with tr.span("decode.emit", batch=inf.batch,
                     layout=inf.layout) as emit_sp:
            for slot, req in zip(inf.slots, inf.reqs):
                i = slot.index
                if slot.request is not req:
                    if not done[i]:
                        raise RuntimeError(
                            f"async stop-condition drift: slot {i} "
                            f"was evicted on the host but tick "
                            f"{inf.tick}'s device lane is not done")
                    continue
                slot.pos += 1
                self._pos[i] = slot.pos
                self._emit(slot, int(ids[i]))
                emitted += 1
                if bool(done[i]) != (slot.request is None):
                    raise RuntimeError(
                        f"async stop-condition drift: slot {i} host "
                        f"finished={slot.request is None} vs device "
                        f"done={bool(done[i])} at tick {inf.tick}")
            emit_sp.args["emitted"] = emitted
        return emitted

    # -- ragged paged attention dispatch (attn_impl="ragged") ----------
    def _plan_ragged_chunks(self, prefilling):
        """Select this tick's prefill-chunk lanes for the unified
        ragged dispatch: admission order (partially-prefilled prompts
        resume first — ``snapshot()`` already sorts by seq), ONE
        window lane per slot of up to ``min(_wmax, budget left)``
        tokens, strictly capped by ``tick_token_budget`` like
        ``_prefill_chunked``.  The lane width is capped by the
        compiled window ``_wmax`` (= max(prefill_chunk, spec_k+1)),
        not by ``prefill_chunk``: widths are runtime data, so a
        spec-widened window prefills faster than the nominal chunk at
        zero extra cost.  One structural difference from the XLA
        path: a slot advances at most one window per tick (the XLA
        path can spend the whole budget re-dispatching one slot's
        chunks back to back), so per-slot prefill throughput is
        ``_wmax`` tokens/tick — under ``attn_impl="ragged"`` size
        ``prefill_chunk`` to the per-tick prompt throughput you want
        (the budget then mainly arbitrates ACROSS slots).  Returns
        [(slot, n_tokens, is_final_chunk)]."""
        plan = []
        budget = self._tick_budget
        for slot in prefilling:
            req = slot.request
            n = min(self._wmax, budget,
                    len(req.context) - slot.prefilled)
            if n <= 0:
                continue
            plan.append((slot, n,
                         slot.prefilled + n >= len(req.context)))
            budget -= n
            if budget <= 0:
                break
        return plan

    def _dispatch_ragged(self, active, plan, tr):
        """DISPATCH one unified RAGGED window tick without consuming
        it: decoding slots ride as mode-0 lanes (width 1, or the k+1
        verify window with host-proposed drafts), budgeted prefill
        chunks as mode-1/2 lanes (width = chunk tokens) — ONE call of
        the ONE compiled ``ragged_window`` program, whatever the mix.
        Chunk lanes advance the prefill cursor AT DISPATCH (their
        tokens are known up front — unlike spec drafts there is no
        data dependence on the in-flight window), so a depth-2 blind
        dispatch can plan the next chunk, and a final chunk's first
        token rides home in the device picks: chunked prefill
        pipelines instead of forcing a drain per chunk like the XLA
        path's per-chunk programs."""
        import jax.numpy as jnp
        W = self._wmax
        B = self.num_slots
        spec_w = (self._spec_k + 1) if self._spec_k is not None else 1
        toks = np.zeros((B, W), np.int32)
        width = np.zeros(B, np.int32)
        mode = np.zeros(B, np.int32)
        lanes = np.zeros(B, np.int32)
        if self._spec_k is not None and active:
            with tr.span("spec.draft", batch=len(active),
                         spec_k=self._spec_k):
                toks[:, :spec_w] = self._draft_window(active)
        for slot in active:
            width[slot.index] = spec_w
            if self._spec_k is not None:
                lanes[slot.index] = slot.spec_lanes
        chunk_toks = 0
        for slot, n, final in plan:
            req = slot.request
            i = slot.index
            p0 = slot.prefilled
            toks[i, :n] = req.context[p0:p0 + n]
            width[i] = n
            mode[i] = 2 if final else 1
            chunk_toks += n
        # push BEFORE the chunk lanes' mirror advance below: a dirty
        # upload must carry the PRE-dispatch cursors (the program
        # itself advances them by width)
        if self._state_dirty or self._dev_state is None:
            self._push_state()
        variant = "gather" if self.attn_impl == "ragged_gather" \
            else "stream"
        # kv blocks the kernel walks this tick (computed on the
        # PRE-dispatch cursors, before the chunk lanes' mirror
        # advance): the streaming loop stops at each lane's causal
        # horizon ceil((pos + width) / block_size), while the gather
        # body always concatenates the slot's FULL table — the
        # per-tick block-walk cost the kv_blocks_walked_per_tick
        # gauge makes attributable (and the serving_longctx bench
        # plots flat vs context length for the streaming variant)
        walked = 0
        for s in (list(active) + [sl for sl, _, _ in plan]):
            i = s.index
            if variant == "gather":
                walked += self._bps
            else:
                live = int(self._pos[i]) + max(int(width[i]), 1)
                walked += min(self._bps, (live - 1) // self._bs + 1)
        self._m_kv_blocks_walked.set(walked)
        for slot, n, final in plan:
            i = slot.index
            # dispatch-time bookkeeping (kept consistent with the
            # device cursor the program advances; the mirrors equal
            # the post-consume state, so a drain-then-push re-upload
            # stays exact)
            slot.prefilled += n
            slot.pos = slot.prefilled
            self._pos[i] = slot.prefilled
            self._m_chunks.inc()
            self._m_prefill_tokens.inc(n)
        st = self._dev_state
        if self._ragged_fn is None:
            # emit_w: sample only the emit-reachable lanes (spec_k+1,
            # or 1 without speculation) — a chunk-widened window's
            # high lanes can never emit, so their picks would be
            # computed and discarded every tick
            self._ragged_fn, _, _ = \
                self.model._compiled_ragged_window_fn(
                    self._pnames, self._params,
                    self._lora_key(
                        (self.num_slots, W, spec_w,
                         self._kv_managed + self.dp, self._bs,
                         self._kv_dtype_str, tuple(self._pnames),
                         self._bnames_all)),
                    emit_w=spec_w, variant=variant,
                    sharded=self.mp * self.dp > 1)
        self._fault("dispatch")
        span_name = "decode.ragged_stream" if variant == "stream" \
            else "decode.ragged"
        with tr.span(span_name, batch=len(active) + len(plan),
                     layout="paged", w=W, chunks=len(plan),
                     chunk_tokens=chunk_toks, fused=True,
                     kv_blocks_walked=walked), \
                self._dequant_span(tr, len(active) + len(plan)):
            (picks, n_acc, n_emit, done, new_tok, new_pos, new_ctr,
             new_rem, self.k_pools, self.v_pools) = self._ragged_fn(
                self._p_list(), self._b_list(), self.k_pools,
                self.v_pools, st["tables"], st["scratch"],
                jnp.asarray(toks),
                jnp.asarray(width), jnp.asarray(mode),
                jnp.asarray(lanes), st["tok"], st["pos"], st["temp"],
                st["topk"], st["topp"], st["slo"], st["shi"],
                st["ctr"], st["eos"], st["rem"],
                *self._lora_args_state(st))
        st["tok"], st["pos"], st["ctr"], st["rem"] = \
            new_tok, new_pos, new_ctr, new_rem
        self._m_fused_ticks.inc()
        if self._spec_k is not None and active:
            self._m_spec_windows.inc(len(active))
        slots = list(active) + [s for s, _, _ in plan]
        return _InflightTick(
            self.tick_no, "ragged", slots,
            {"picks": picks, "n_acc": n_acc, "n_emit": n_emit,
             "done": done}, len(slots), "paged",
            {"pos": self._pos.tolist(), "rem": self._rem.tolist()},
            meta_lanes=[(int(mode[s.index]), int(width[s.index]),
                         int(lanes[s.index])) for s in slots])

    def _consume_ragged(self, inf, mats, done, tr):
        """Emit a materialized ragged tick, per lane MODE: chunk lanes
        (mode 1) already advanced at dispatch — nothing to emit; a
        final chunk (mode 2) registers the prompt's full blocks in the
        prefix cache and emits the device-sampled first token (picks
        lane 0 — drawn with the unshifted counter key, the stream's
        next draw); decode / spec lanes (mode 0) run the same
        accepted-prefix emit loop as ``_consume_spec``, a pure decode
        lane being its zero-draft degenerate case.  Host-vs-device
        drift in any mode raises into step recovery."""
        picks = mats["picks"]
        n_acc = mats["n_acc"]
        n_emit_dev = mats["n_emit"]
        emitted = 0
        total_acc = 0
        emitted_spec = 0
        n_spec = 0
        with tr.span("decode.emit", batch=inf.batch,
                     layout=inf.layout) as emit_sp:
            for slot, req, (mode_i, width_i, lanes_i) in zip(
                    inf.slots, inf.reqs, inf.meta_lanes):
                i = slot.index
                if slot.request is not req:
                    if not done[i]:
                        raise RuntimeError(
                            f"async stop-condition drift: slot {i} "
                            f"was evicted on the host but tick "
                            f"{inf.tick}'s device lane is not done")
                    continue
                if mode_i == 1:
                    if int(n_emit_dev[i]):
                        raise RuntimeError(
                            f"ragged drift: chunk lane {i} emitted "
                            f"{int(n_emit_dev[i])} on device at tick "
                            f"{inf.tick}")
                    continue
                if mode_i == 2:
                    ctxt = req.context
                    if self.prefix_cache is not None \
                            and not req._adapter_id:
                        self.prefix_cache.insert(
                            ctxt,
                            self._slot_blocks[i][:len(ctxt)
                                                 // self._bs])
                    self._emit(slot, int(picks[i, 0]))
                    emitted += 1
                    if int(n_emit_dev[i]) != 1 or \
                            bool(done[i]) != (slot.request is None):
                        raise RuntimeError(
                            f"ragged drift: final-chunk lane {i} "
                            f"device n_emit={int(n_emit_dev[i])} "
                            f"done={bool(done[i])} vs host finished="
                            f"{slot.request is None} at tick "
                            f"{inf.tick}")
                    continue
                # mode 0: decode / spec window — the same emit loop
                # as _consume_spec (zero draft lanes = plain decode)
                if self._spec_k is not None:
                    self._m_spec_proposed.inc(lanes_i)
                    n_spec += 1
                n_em, n_cnt = self._emit_window_lane(
                    slot, picks[i], int(n_acc[i]),
                    int(n_emit_dev[i]), bool(done[i]), inf.tick)
                if self._spec_k is not None:
                    self._m_spec_accepted.inc(n_cnt)
                    total_acc += n_cnt
                emitted_spec += n_em
                emitted += n_em
            emit_sp.args.update(emitted=emitted, accepted=total_acc)
        if self._spec_k is not None and n_spec:
            proposed = self._m_spec_proposed.value
            if proposed:
                self._m_spec_rate.set(
                    self._m_spec_accepted.value / proposed)
            self._m_spec_tpt.set(emitted_spec / n_spec)
        return emitted

    def _consume(self, inf, tr):
        """Materialize and emit one in-flight tick.  The blocking
        ``np.asarray`` on the ids + done mask is the async loop's ONLY
        sync point — traced as ``decode.d2h_wait`` (``decode.d2h`` at
        async_depth=1, today's synchronous name) so the wait is
        attributed to the download, not smeared into dispatch.  When
        a newer tick is still in flight, the emit work is wrapped in
        a ``host.overlap`` span and counted into
        ``serving.tick_overlap_ms`` — the host time the pipeline hid
        behind device compute."""
        wait_name = ("decode.d2h_wait" if self.async_depth > 1
                     else "decode.d2h")
        # the injectable wedge: a scheduled d2h_hang blocks here (the
        # engine's real sync point) until the watchdog converts it
        # into a WatchdogTimeout raise -> step-failure recovery
        self._fault("d2h_hang")
        if self.mp * self.dp > 1:
            # sharded tick: the [B] ids / picks are OUTPUTS of a
            # vocab-parallel head (replicated over 'mp' by its psum +
            # all-gather) and row-sharded over 'dp' — the device
            # finishes the cross-shard collectives before the handles
            # are ready.  Block on compute completion FIRST under its
            # own span so collective time is attributed to
            # decode.allgather, and the d2h span below measures the
            # (tiny, unchanged-contract) host copy alone.
            with tr.span("decode.allgather", tick=inf.tick,
                         shards=self.mp * self.dp, mp=self.mp,
                         dp=self.dp):
                for v in inf.arrays.values():
                    v.block_until_ready()
        t0 = time.monotonic()
        with tr.span(wait_name, tick=inf.tick) as d2h_sp:
            mats = {k: np.asarray(v) for k, v in inf.arrays.items()}
            nbytes = sum(int(a.nbytes) for a in mats.values())
            d2h_sp.args["bytes"] = nbytes
        self._m_d2h_wait.observe((time.monotonic() - t0) * 1e3)
        self._m_d2h.set(nbytes)
        done = np.unpackbits(mats["done"],
                             count=self.num_slots).astype(bool)
        in_flight = bool(self._ring)
        t1 = time.monotonic()
        ov = (tr.span("host.overlap", tick=inf.tick) if in_flight
              else nullcontext())
        with ov:
            if inf.kind == "spec":
                emitted = self._consume_spec(inf, mats, done, tr)
            elif inf.kind == "ragged":
                emitted = self._consume_ragged(inf, mats, done, tr)
            else:
                emitted = self._consume_decode(inf, mats, done, tr)
        if in_flight:
            self._overlap_acc += time.monotonic() - t1
        return emitted

    def _note_dispatch_gap(self, n_active):
        """Pre-dispatch bookkeeping shared by the sync, async, and
        ragged tick paths (stall histogram + decode-batch gauge):
        ONE implementation, so the stall accounting cannot diverge
        between attn_impl modes or pipeline depths."""
        if self._last_decode_end is not None:
            self._m_stall.observe(
                (time.monotonic() - self._last_decode_end) * 1e3)
        self._m_decode_batch.set(n_active)

    def _drain_ring(self, tr):
        """Consume every in-flight tick, oldest first (the dirty-event
        barrier: mirrors may only be re-uploaded over an empty
        pipeline).  Returns tokens emitted."""
        emitted = 0
        while self._ring:
            emitted += self._consume(self._ring.pop(0), tr)
        return emitted

    def _fused_decode_tick(self, active):
        """Synchronous fused decode tick (async_depth=1 and the
        host-driven ``_tick`` path): dispatch + immediate consume —
        today's tick shape, bit-for-bit."""
        inf = self._dispatch_decode(active, self.tracer)
        return self._consume(inf, self.tracer)

    def _decode_tick(self, active):
        """One slot-batched decode dispatch; samples and advances every
        live slot (speculative mode verifies a whole draft window per
        slot instead; sample_mode="device" routes both shapes to their
        fused on-device-sampling twins)."""
        import jax.numpy as jnp
        if self._spec_k is not None:
            if self.sample_mode == "device":
                return self._fused_spec_tick(active)
            return self._spec_decode_tick(active)
        if self.sample_mode == "device":
            return self._fused_decode_tick(active)
        if self._tick_fn is None:
            # resolve once: the key embeds tuple(pnames), an O(n_params)
            # copy+hash not worth paying per generated token
            if self._paged:
                self._tick_fn, _, _ = \
                    self.model._compiled_slot_paged_decode_fn(
                        self._pnames, self._params,
                        (self.num_slots, self._kv_managed + self.dp, self._bs,
                         self._kv_dtype_str, tuple(self._pnames),
                         self._bnames_all))
            else:
                self._tick_fn, _, _ = self.model._compiled_slot_decode_fn(
                    self._pnames, self._params,
                    (self.num_slots, self.max_seq_len,
                     self._kv_dtype_str, tuple(self._pnames),
                     self._bnames_all))
        fn = self._tick_fn
        tr = self.tracer
        layout = "paged" if self._paged else "contiguous"
        self._fault("dispatch")
        with tr.span("decode.dispatch", batch=len(active),
                     layout=layout):
            if self._paged:
                last, self.k_pools, self.v_pools = fn(
                    self._p_list(), self._b_list(), self.k_pools,
                    self.v_pools, jnp.asarray(self._block_tables),
                    jnp.asarray(self._cur_tok), jnp.asarray(self._pos))
            else:
                last, self.k_pools, self.v_pools = fn(
                    self._p_list(), self._b_list(), self.k_pools,
                    self.v_pools, jnp.asarray(self._cur_tok),
                    jnp.asarray(self._pos))
        with tr.span("decode.d2h") as d2h_sp:
            rows = np.asarray(last, np.float32)
            d2h_sp.args["bytes"] = rows.nbytes
        self._m_d2h.set(rows.nbytes)
        t_sample = time.monotonic()
        emitted = 0
        with tr.span("decode.sample", batch=len(active),
                     layout=layout) as sample_sp:
            for slot in active:
                slot.pos += 1
                self._pos[slot.index] = slot.pos
                self._emit(slot, self._pick(slot.request,
                                            rows[slot.index]))
                emitted += 1
            sample_sp.args["emitted"] = emitted
        self._m_sample_ms.observe((time.monotonic() - t_sample) * 1e3)
        return emitted

    def step(self):
        """One engine tick: admit -> prefill -> slot-batched decode.
        Returns the number of tokens emitted this tick.

        A tick that raises (transient XLA error, bad dispatch) first
        RECOVERS the engine — in-flight requests are failed loudly
        (their waiters unblock) and the donated K/V pools are rebuilt
        (a dispatch that died after consuming them leaves them deleted)
        — then re-raises, so every driver (run_until_idle, bench, the
        background loop) sees a working engine afterwards."""
        # O(1) no-op while subscribed; re-subscribes a synchronous
        # driver that keeps ticking after a stop()
        self._register_compile_listener()
        if self.watchdog_s is not None and self._watchdog is None:
            from .faults import TickWatchdog
            self._watchdog = TickWatchdog(self, self.watchdog_s).start()
        try:
            return self._step_inner()
        except Exception as e:
            # flight recorder FIRST: the dump must capture the slot /
            # request states as they were at the failure, not after
            # the evictions below tear them down
            self._record_flight(e)
            # busy_slots, not active_slots: a chunked tick that dies
            # mid-prompt leaves half-PREFILLED slots whose waiters must
            # unblock just like the decoding ones
            for slot in self.scheduler.busy_slots():
                req = self.scheduler.evict(slot, RuntimeError(
                    f"engine step failed: {e!r}"))
                if req is not None:
                    self._rngs.pop(req.id, None)
                    self._m_done.inc()  # terminal, like timeouts: keep
                    #   in-flight = total - completed consistent
                    self.tracer.instant("req.evicted", cat="request",
                                        req=req.id,
                                        reason="step_failure")
            self._reset_pools()
            self._last_decode_end = None
            self._m_occ.set(0)
            raise

    def _step_inner(self):
        self.tick_no += 1
        tr = self.tracer
        # watchdog heartbeat: stamped for the tick's whole duration;
        # a stale stamp is how the watchdog detects a wedged tick
        self._watchdog_fired = False
        self._tick_started_at = time.monotonic()
        try:
            self._fault("host_slow")
            with tr.span("tick", cat="tick",
                         tick=self.tick_no) as tick_sp:
                if self.async_depth > 1:
                    emitted = self._tick_async(tr, tick_sp)
                else:
                    emitted = self._tick(tr, tick_sp)
        finally:
            self._tick_started_at = None
        if emitted:
            now = time.monotonic()
            with self._ovl_lock:
                self._rate_win.append((now, emitted))
            rate = self.drain_rate()
            if rate is not None:
                self._m_drain_tps.set(round(rate, 1))
        return emitted

    def _tick_async(self, tr, tick_sp):
        """One PIPELINED engine tick (async_depth > 1): plan/admit in
        the gap while the previous tick computes, dispatch tick N+1,
        then consume tick N's already-materializing ids — so the
        inter-tick host work (admission, chunk planning, the emit
        loop) hides behind device compute instead of serializing with
        it.  Structural events (admission, eviction, chunk) dirty the
        host mirrors; the pipeline is drained before the mirrors are
        re-uploaded, so parity with the synchronous tick is exact."""
        self._overlap_acc = 0.0
        now = time.monotonic()
        emitted = 0
        # cross-replica migration orders first: an export drains the
        # ring and frees its slot for this very tick's admission, an
        # import's request enters the queue before the admit phase
        emitted += self._service_migrations(tr)
        # ...then the offload drain: last tick's demote gathers have
        # had a dispatch of device time — copy out behind it
        self._service_offload(tr)
        # -- planning / admission: host work in the gap --------------
        in_flight = bool(self._ring)
        t_plan = time.monotonic()
        self._gate_declined = False
        ov = (tr.span("host.overlap", phase="plan") if in_flight
              else nullcontext())
        with ov:
            with tr.span("admit") as admit_sp:
                timed_out = self.queue.expire(now)
                admitted = []
                if not self._draining and self.scheduler.admissible():
                    admitted, admit_timed_out = self.scheduler.admit(
                        now, gate=self._kv_gate if self._paged
                        else None)
                    timed_out = timed_out + admit_timed_out
                admit_sp.args.update(admitted=len(admitted),
                                     timed_out=len(timed_out))
        if in_flight:
            self._overlap_acc += time.monotonic() - t_plan
        # priority preemption (outside the overlap span: it may have
        # to consume the in-flight ring — a real sync, not hidden
        # host work)
        p_admitted, p_timed, p_emitted = self._preempt_round(now, tr)
        emitted += p_emitted
        admitted = self._post_admit(admitted + p_admitted,
                                    timed_out + p_timed, tr)
        # -- prefill / chunk planning (mutates only the admitted
        #    slots' lanes; the dirty flag defers the re-upload) ------
        if self._chunk is None:
            for slot in admitted:
                rid = slot.request.id
                with tr.span("prefill", req=rid,
                             prompt=int(len(slot.request.prompt))):
                    self._prefill(slot)
                emitted += 1  # prefill samples the first token
        else:
            for slot in admitted:
                self._begin_chunked(slot)
            _, _, prefilling = self.scheduler.snapshot()
            if prefilling and not self._ragged:
                # ragged mode: chunks ride as lanes of the unified
                # dispatch below — and because their tokens are known
                # up front (no data dependence on the in-flight
                # window), chunk progress needs NO pipeline drain,
                # unlike the XLA per-chunk programs whose cursor
                # updates dirty the mirrors every chunk
                n_emit, _, _ = self._prefill_chunked(prefilling)
                emitted += n_emit
        # -- spec barrier: drafting is data-dependent on the previous
        #    window's accepted tokens, so spec mode always consumes
        #    before the dispatch snapshot — but only HERE, after the
        #    planning/prefill phase above ran in the gap, so spec
        #    ticks still overlap their plan work with the in-flight
        #    verify's device compute --------------------------------
        if self._spec_k is not None and self._ring:
            emitted += self._drain_ring(tr)
        # -- dirty barrier: consumed evictions must not leave freed
        #    slots in the dispatch set, and _push_state may only run
        #    over an empty pipeline ---------------------------------
        if self._ring and (self._state_dirty or self._dev_state is None):
            emitted += self._drain_ring(tr)
        occ, active, prefilling = self.scheduler.snapshot()
        ragged = self._ragged
        if active and self._ring and self._spec_k is None and \
                not (ragged and prefilling) and \
                all(self._rem[s.index] <= len(self._ring)
                    for s in active):
            # bursty-tail cutoff: the rem mirrors say every active
            # slot exhausts its budget within the ticks ALREADY in
            # flight, so one more dispatch would compute only frozen
            # lanes — consume instead (EOS can still finish a lane
            # earlier than its budget; that case just falls through
            # to the done-mask path).  Pending ragged chunk lanes
            # veto the cutoff: their dispatch still does real work.
            emitted += self._drain_ring(tr)
            occ, active, prefilling = self.scheduler.snapshot()
        n_before = self._evicted_in_tick
        plan = (self._plan_ragged_chunks(prefilling)
                if ragged and self._chunk is not None else [])
        # -- dispatch tick N+1 ---------------------------------------
        if active or plan:
            self._note_dispatch_gap(len(active))
            if ragged:
                inf = self._dispatch_ragged(active, plan, tr)
            else:
                inf = (self._dispatch_spec(active, tr)
                       if self._spec_k is not None
                       else self._dispatch_decode(active, tr))
            self._ring.append(inf)
            self._last_decode_end = time.monotonic()
        else:
            self._m_decode_batch.set(0)
            self._last_decode_end = None
        # -- consume tick N (the emit loop overlaps N+1's compute);
        #    with nothing dispatched, drain the tail completely ------
        keep = (self.async_depth - 1) if (active or plan) else 0
        while len(self._ring) > keep:
            emitted += self._consume(self._ring.pop(0), tr)
        occ -= self._evicted_in_tick - n_before
        if self._ring and occ == 0:
            # every slot freed while the newest dispatch was in
            # flight: its lanes are all frozen (device-side stop), so
            # drain the tail — an idle engine must hold no futures
            emitted += self._drain_ring(tr)
        self._m_queue.set(self.queue.depth())
        self._m_occ.set(occ)
        ov_ms = self._overlap_acc * 1e3
        self._m_overlap.observe(ov_ms)
        tick_sp.args.update(batch=len(active), emitted=emitted,
                            occupancy=occ, queue=self.queue.depth(),
                            overlap_ms=round(ov_ms, 3),
                            in_flight=len(self._ring))
        if self._paged:
            self._m_kv_blocks.set(self.block_pool.in_use())
            tick_sp.args["kv_blocks_in_use"] = self.block_pool.in_use()
        return emitted

    def _tick(self, tr, tick_sp):
        now = time.monotonic()
        emitted = 0
        # cross-replica migration orders first (see _tick_async)
        emitted += self._service_migrations(tr)
        self._service_offload(tr)  # tick-boundary demote drain
        self._gate_declined = False
        # deadline sweep first: with a full pool nothing gets popped,
        # but queued requests must still time out on schedule
        with tr.span("admit") as admit_sp:
            timed_out = self.queue.expire(now)
            admitted = []
            if not self._draining:
                admitted, admit_timed_out = self.scheduler.admit(
                    now, gate=self._kv_gate if self._paged else None)
                timed_out = timed_out + admit_timed_out
            admit_sp.args.update(admitted=len(admitted),
                                 timed_out=len(timed_out))
        # priority preemption: evict the lowest-priority running slot
        # when the best queued request outranks it and admission is
        # blocked (no async ring at depth 1, so no drain involved)
        p_admitted, p_timed, p_emitted = self._preempt_round(now, tr)
        emitted += p_emitted
        admitted = self._post_admit(admitted + p_admitted,
                                    timed_out + p_timed, tr)
        if self._chunk is None:
            for slot in admitted:
                # read the id up front: an EOS-on-first-token prefill
                # evicts and clears slot.request before the span ends
                rid = slot.request.id
                with tr.span("prefill", req=rid,
                             prompt=int(len(slot.request.prompt))):
                    self._prefill(slot)
                emitted += 1  # prefill samples the first token
            occ, active, prefilling = self.scheduler.snapshot()
        else:
            for slot in admitted:
                self._begin_chunked(slot)
            occ, active, prefilling = self.scheduler.snapshot()
            if prefilling and not self._ragged:
                # ragged mode skips the per-chunk dispatch loop —
                # chunks ride as window lanes of the unified dispatch
                n_emit, newly, n_evicted = \
                    self._prefill_chunked(prefilling)
                emitted += n_emit
                occ -= n_evicted
                active = active + newly  # final-chunk slots decode in
                #   this same tick, like monolithic emit-then-decode
        if self._ragged:
            plan = (self._plan_ragged_chunks(prefilling)
                    if self._chunk is not None else [])
            if active or plan:
                self._note_dispatch_gap(len(active))
                n_before = self._evicted_in_tick
                inf = self._dispatch_ragged(active, plan, tr)
                emitted += self._consume(inf, tr)
                occ -= self._evicted_in_tick - n_before
                self._last_decode_end = time.monotonic()
            else:
                self._m_decode_batch.set(0)
                self._last_decode_end = None
        elif active:
            self._note_dispatch_gap(len(active))
            n_before = self._evicted_in_tick
            emitted += self._decode_tick(active)
            occ -= self._evicted_in_tick - n_before
            self._last_decode_end = time.monotonic()
        else:
            self._m_decode_batch.set(0)
            self._last_decode_end = None
        self._m_queue.set(self.queue.depth())
        self._m_occ.set(occ)
        tick_sp.args.update(batch=len(active), emitted=emitted,
                            occupancy=occ, queue=self.queue.depth())
        if self._paged:
            self._m_kv_blocks.set(self.block_pool.in_use())
            tick_sp.args["kv_blocks_in_use"] = self.block_pool.in_use()
        return emitted

    def run_until_idle(self, max_steps=100000):
        """Drive ticks until queue and slots are empty (test/batch
        convenience); returns total tokens emitted."""
        total = 0
        for _ in range(max_steps):
            if self.scheduler.idle() and not self._migrate_actionable():
                self._flush_offload()  # last tick's demotes land
                return total
            total += self.step()
        raise RuntimeError(
            f"engine still busy after {max_steps} steps "
            f"(occupancy={self.scheduler.occupancy()}, "
            f"queue={self.queue.depth()})")

    # -- background loop -------------------------------------------------
    def start(self):
        """Run the tick loop on a daemon thread (the HTTP endpoint's
        mode); idle ticks sleep briefly instead of spinning.  Safe to
        call after a timed-out stop(): the new loop joins the old one
        before its first tick, so two loops never step concurrently."""
        self._register_compile_listener()  # restart after a stop()
        prev = self._thread
        if prev is not None and prev.is_alive() \
                and not self._stop.is_set():
            return prev  # loop already running
        if prev is not None and not prev.is_alive():
            prev = None
        # a restart supersedes a pending shutdown drain: the old loop
        # must not fail requests submitted to the restarted engine
        # (the flag is the owning loop's stop event, so a stale loop
        # comparing against its own event can never match after this)
        self._drain_on_exit = None
        self._draining = False  # a restarted engine admits again
        # each loop carries its OWN stop event: a stop-pending loop
        # keeps honoring the event it was born with while the new loop
        # runs against the fresh one
        stop_evt = self._stop = threading.Event()

        def loop():
            if prev is not None:
                prev.join()  # serialize: never two loops in step()
            try:
                while not stop_evt.is_set():
                    if self.scheduler.idle() \
                            and not self._migrate_actionable():
                        self._m_rate.refresh()  # decay tokens/sec to 0
                        # event-driven wake instead of a 2 ms poll: an
                        # idle engine burns no CPU and a submit() is
                        # admitted immediately, not a poll later.  The
                        # clear-then-recheck order closes the race: a
                        # submit landing between the idle check and
                        # the clear is caught by the recheck, one
                        # landing after it re-sets the event.  The
                        # timeout is only the tokens/sec decay + stop
                        # heartbeat, not an admission latency bound.
                        self._flush_offload()  # going idle: land the
                        #   final tick's demote gathers now
                        self._wake.clear()
                        if self.scheduler.idle() \
                                and not self._migrate_actionable() \
                                and not stop_evt.is_set():
                            self._wake.wait(timeout=0.5)
                        continue
                    try:
                        self.step()  # step() already recovered state
                    except Exception:  # keep the loop alive
                        time.sleep(0.05)  # no hot spin on repeat failure
            finally:
                # a stop() whose join might time out delegates the
                # drain here (the loop's last act); the identity check
                # means only THIS loop's stop() can trigger it — a
                # restart invalidates stale delegations
                if self._drain_on_exit is stop_evt:
                    self._drain_on_exit = None
                    self._drain()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="paddle_tpu-serving-engine")
        self._thread.start()
        return self._thread

    def _drain(self):
        """Fail every queued and in-flight request (shutdown path)."""
        self._flush_offload()  # land pending demotes — the host tier
        #   outlives this loop and warms the next start()
        # drop un-consumed dispatches: their requests fail below, and
        # the next start() re-uploads clean cursors (every eviction
        # parks its lanes and dirties the mirrors)
        self._ring = []
        with self._mig_lock:
            demands, self._migrate_demands = self._migrate_demands, []
        for d in demands:
            d.fail(RuntimeError("engine stopped"))
        for req in self.queue.drain():
            # a preempted host-mode request waiting in queue still
            # holds its numpy rng stream — shutdown must release it
            self._rngs.pop(req.id, None)
            self._m_done.inc()
        for slot in self.scheduler.busy_slots():
            req = self.scheduler.evict(
                slot, RuntimeError("engine stopped"))
            self._release_slot_kv(slot.index)
            self._park_state(slot.index)  # a later start() serves with
            #   clean device-mode cursors
            if req is not None:
                self._rngs.pop(req.id, None)
                self._m_done.inc()
                self.tracer.instant("req.evicted", cat="request",
                                    req=req.id, reason="shutdown")
        self._m_queue.set(0)
        self._m_occ.set(0)

    def stop(self, drain=True, join_timeout=30.0, drain_timeout=None):
        """Stop the background loop.

        ``drain=True`` (default) is a GRACEFUL DRAIN: submission
        closes (``submit`` sheds with QueueFull) and no queued
        request is admitted, but the loop keeps ticking until every
        IN-FLIGHT stream finishes — their waiters receive complete
        outputs instead of an "engine stopped" error.  The wait is
        bounded by ``drain_timeout`` (default: ``join_timeout``);
        whatever is still running past the bound, plus every
        queued-but-never-admitted request, is failed by the final
        hard drain — shutdown always terminates.  ``drain=False``
        halts the loop in place without failing anything (requests
        stay pending for a later ``start()``)."""
        evt = self._stop
        t = self._thread
        if drain and t is not None and t.is_alive() \
                and not evt.is_set():
            # graceful phase: the live loop finishes the in-flight
            # streams while admissions are held off
            self._draining = True
            self._wake.set()
            limit = (join_timeout if drain_timeout is None
                     else drain_timeout)
            deadline = time.monotonic() + max(float(limit), 0.0)
            while time.monotonic() < deadline \
                    and self.scheduler.busy_slots():
                time.sleep(0.002)
        if drain:
            # delegate BEFORE set+join: a loop that exits inside the
            # join window must still see the delegation (it drains in
            # its finally; double-drain below is an idempotent no-op)
            self._drain_on_exit = evt
        evt.set()
        self._wake.set()  # unblock an idle loop's event wait now
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None  # a later step()/start() re-arms
        if t is not None:
            t.join(timeout=join_timeout)
            if t.is_alive():
                # mid-dispatch (e.g. a long first compile): draining
                # under the live loop would race it, so the loop drains
                # on exit instead; the handle stays so a later start()
                # serializes behind it — and the compile listener stays
                # subscribed, because that in-flight dispatch may be
                # the very compile worth recording.  Clear the drain
                # flag NOW: the stop event already keeps this loop
                # from admitting again, and a later synchronous
                # driver (step() after stop() is supported) must not
                # find admissions permanently disabled
                self._draining = False
                return
            self._thread = None
        # only AFTER the loop is confirmed down: a stopped engine must
        # not keep counting sibling engines' compiles, but compiles
        # completing inside the join window above still count.
        # start() — or a synchronous step() — re-subscribes.
        self._unregister_compile_listener()
        if drain:
            self._drain_on_exit = None
            self._drain()
        self._draining = False  # a later start() serves normally

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
