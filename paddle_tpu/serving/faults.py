"""Deterministic fault injection + tick watchdog for the engine.

The engine's recovery paths (step-failure eviction, pool rebuild,
flight recorder, async ring drop) were each built against ONE
hand-injected failure.  Production failures compose: a dispatch error
lands while a tick is in flight, the pool runs dry during the
recovery re-admission, the host stalls mid-consume.  This module makes
that composition testable and REPRODUCIBLE:

* ``FaultInjector`` — named failure points (``SITES``) threaded
  through engine / kvcache / spec.  Whether a site fires at a given
  engine tick is a PURE FUNCTION of ``(seed, site, tick)`` (a blake2b
  hash against the site's configured rate), so a storm's schedule is
  reproducible from its seed alone, independent of wall-clock timing,
  thread interleaving, or how many times a site is consulted — plus
  explicit one-shot entries via ``at(tick, site)`` for targeted tests.
  The injector records every fired (tick, site) in ``log``; the chaos
  tests assert the same seed replays the same log.

* ``TickWatchdog`` — a daemon thread that watches the engine's
  tick-start heartbeat.  A tick that exceeds ``timeout_s`` (a wedged
  in-flight dispatch, a hung d2h) gets flight-recorded IMMEDIATELY
  (``Engine.last_flight`` snapshots the in-flight state while it is
  still observable) and the engine is marked ``_watchdog_fired`` —
  cooperative blocking points (the injected d2h hang, and any real
  wait loop that polls the flag) convert the wedge into a
  ``WatchdogTimeout`` raise, which lands in the EXISTING
  step-failure recovery path: waiters unblock, pools rebuild, the
  engine serves on.  A truly uninterruptible wedge (real hardware
  hang) still gets the flight dump and an unhealthy mark instead of
  a silent freeze.

Fault sites (who checks them, what firing does):

====================  ===============================  ==============
site                  checked at                        action
====================  ===============================  ==============
``dispatch``          decode / spec-verify dispatch     raises
                      (engine)                          InjectedFault
``d2h_hang``          consume-side materialize          hangs
                      (engine)                          ``hang_s``
                                                        (watchdog
                                                        converts to
                                                        a raise)
``pool_exhaust``      BlockPool.alloc (kvcache hook)    raises
                                                        NoFreeBlocks
``host_slow``         tick start (engine)               sleeps
                                                        ``slow_s``
``spec_draft``        proposer call (engine spec        raises inside
                      draft loop)                       the draft
                                                        try — the
                                                        engine
                                                        degrades to
                                                        zero drafts
====================  ===============================  ==============

Network-layer sites (the ROUTER tier's chaos vocabulary — checked by
a replica TRANSPORT, e.g. ``serving.router.InProcessReplica`` or a
test fake, with the transport's own per-replica operation counter as
the ``tick``; the schedule stays a pure function of (seed, site,
tick) so a seeded replica-kill storm replays exactly):

====================  ===============================  ==============
site                  checked at                        action
====================  ===============================  ==============
``net_refuse``        connection open (transport)       raises
                                                        NetRefused —
                                                        the replica's
                                                        port is
                                                        closed
``net_blackhole``     request dispatch (transport)      waits
                                                        ``blackhole_s``
                                                        cooperatively,
                                                        then raises
                                                        NetTimeout —
                                                        packets
                                                        vanish, the
                                                        client's
                                                        socket
                                                        timeout fires
``net_slow``          request dispatch (transport)      sleeps
                                                        ``net_slow_s``
                                                        and PROCEEDS
                                                        (degraded,
                                                        not dead)
``net_disconnect``    response body (transport)         raises
                                                        NetDisconnect
                                                        mid-body; the
                                                        transport
                                                        attaches the
                                                        tokens
                                                        emitted so
                                                        far, so a
                                                        failover can
                                                        resume with
                                                        context
====================  ===============================  ==============

Migration sites (KV block migration between replicas — checked on the
SOURCE engine's tick for export, the transport's op counter for the
wire, and the DESTINATION engine's tick for import, so a single seeded
schedule can kill a migration at any of its three stages):

====================  ===============================  ==============
site                  checked at                        action
====================  ===============================  ==============
``migrate_export``    source engine, before the slot    raises
                      is frozen and its blocks          InjectedFault
                      gathered (the stream keeps        — migration
                      running on the source)            declined
``migrate_wire``      transport, payload in flight      raises
                      (the bytes may be lost; the       NetDisconnect
                      HOLDER of the payload re-sends
                      or falls back to failover)
``migrate_import``    destination engine, before the    raises
                      gathered blocks are adopted       InjectedFault
                      into its pool/trie (fresh         — destination
                      allocation rolls back to          owns NOTHING
                      refcount 0)
====================  ===============================  ==============

Process-level sites (the SUPERVISOR tier's chaos vocabulary —
checked by the kill-storm driver that owns the replica processes,
with its own storm step counter as the ``tick``; the supervisor
itself never consults the schedule, it only observes and heals the
damage, so supervisor-on and supervisor-off runs of the same seed
see the IDENTICAL fault sequence):

====================  ===============================  ==============
site                  checked at                        action
====================  ===============================  ==============
``proc_kill9``        storm driver, per storm step      SIGKILL to the
                      (``fire(..., proc=popen)``)       target replica
                                                        process — the
                                                        supervisor
                                                        sees the exit
                                                        and restarts
                                                        it with
                                                        backoff
``proc_stop``         storm driver, per storm step      SIGSTOP — a
                      (``fire(..., proc=popen)``)       WEDGE: the
                                                        process stays
                                                        alive but
                                                        /livez times
                                                        out; the
                                                        supervisor
                                                        declares it
                                                        wedged after
                                                        ``wedge_after``
                                                        failed probes,
                                                        SIGKILLs, and
                                                        restarts
``proc_crashloop``    storm driver, per storm step      calls ``arm()``
                      (``fire(..., arm=callable)``)     — the driver's
                                                        hook makes the
                                                        replica's NEXT
                                                        boots exit
                                                        immediately
                                                        (httpd
                                                        ``--fail-boot-
                                                        below``); the
                                                        supervisor's
                                                        crash-loop
                                                        window trips
                                                        and the
                                                        replica ends
                                                        QUARANTINED
====================  ===============================  ==============

Front-end sites (the LoRA + streaming tier — ``adapter_load`` is
checked by the engine while servicing a hot load/unload demand with
the servicing tick as the ``tick``; ``stream_disconnect`` by a
streaming consumer with its per-server stream ordinal as the
``tick``):

====================  ===============================  ==============
site                  checked at                        action
====================  ===============================  ==============
``adapter_load``      engine, servicing a               raises
                      load_adapter / unload_adapter     InjectedFault
                      demand (before touching the       — the demand
                      banks)                            fails, banks
                                                        and inventory
                                                        untouched
``stream_disconnect`` streaming consumer (TokenStream   raises
                      / SSE edge), mid-iteration        StreamDisconnect
                                                        after a
                                                        schedule-
                                                        derived number
                                                        of tokens —
                                                        the client
                                                        vanished; the
                                                        request keeps
                                                        decoding,
                                                        delivered
                                                        tokens stay
                                                        delivered
====================  ===============================  ==============

Offload sites (the host-RAM KV tier, serving/offload.py — both
checked on the OWNING engine's tick, so one seeded schedule covers a
demote and the promote that would have consumed it):

====================  ===============================  ==============
site                  checked at                        action
====================  ===============================  ==============
``offload_demote``    engine, inside the prefix trie's  raises
                      evict hook, BEFORE the gather is  InjectedFault
                      enqueued                          — the block
                                                        frees without
                                                        spilling, the
                                                        host store
                                                        never sees a
                                                        partial entry
``offload_promote``   engine admission gate, after the  raises
                      host probe matched but BEFORE     InjectedFault
                      any entry is read or imported     — admission
                                                        falls back to
                                                        recompute; the
                                                        fresh device
                                                        blocks stay
                                                        plain prefill
                                                        targets, host
                                                        entries stay
                                                        resident
====================  ===============================  ==============
"""
from __future__ import annotations

import hashlib
import signal as _signal
import threading
import time
import weakref


class InjectedFault(RuntimeError):
    """A FaultInjector site fired (the simulated transient failure)."""


class WatchdogTimeout(RuntimeError):
    """The tick watchdog declared an in-flight tick wedged."""


class NetFault(InjectedFault):
    """Base of the injected network-layer failures (router transport
    sites) — subclasses tell the router's retry classifier WHICH
    failure mode it is looking at."""


class NetRefused(NetFault):
    """Injected connection-refused: the replica's port is closed
    (process dead or not yet listening).  Instant and retryable."""


class NetTimeout(NetFault):
    """Injected black hole: the request went out, nothing came back,
    and the client's socket timeout fired.  Retryable — but the
    request MAY have been executed (the loss could be on the response
    path), so only idempotent work should be blindly re-sent."""


class StreamDisconnect(NetFault):
    """Injected streaming-client death: the SSE consumer vanished
    mid-response.  Server side this is indistinguishable from a TCP
    reset — the handler stops writing and releases the stream; the
    tokens already delivered stay delivered (exactly-once), the
    request itself keeps decoding to completion."""


class NetDisconnect(NetFault):
    """Injected mid-body disconnect: the response stream died after
    ``emitted`` tokens were already received.  A failover can resume
    with prompt + emitted as the new context instead of recomputing
    (and for greedy/seeded traffic, the resumed stream is identical
    to the uninterrupted one)."""

    def __init__(self, msg, emitted=None):
        super().__init__(msg)
        self.emitted = list(emitted or [])


ENGINE_SITES = ("dispatch", "d2h_hang", "pool_exhaust", "host_slow",
                "spec_draft")
NET_SITES = ("net_refuse", "net_blackhole", "net_slow",
             "net_disconnect")
MIGRATE_SITES = ("migrate_export", "migrate_wire", "migrate_import")
PROC_SITES = ("proc_kill9", "proc_stop", "proc_crashloop")
# Front-end sites (LoRA + streaming tier): ``adapter_load`` is checked
# by the engine while servicing a load/unload demand (tick = the
# engine tick servicing it) — firing fails THAT demand only, banks and
# inventory untouched; ``stream_disconnect`` is checked by a streaming
# consumer (TokenStream / the SSE edge) with its per-server stream
# ordinal as the tick — firing simulates the client vanishing
# mid-response, which the server loop sees as a dead socket.
FRONTEND_SITES = ("adapter_load", "stream_disconnect")
# Host-RAM offload tier sites (serving/offload.py): ``offload_demote``
# is checked by the prefix trie's evict hook — firing drops the spill,
# the block frees WITHOUT entering the host store (pre-offload
# behavior, store untouched); ``offload_promote`` is checked by the
# admission gate's host-tier consult — firing falls back to recompute,
# the fresh device blocks stay plain prefill targets and the host
# entries stay resident.  Neither firing can corrupt either tier.
OFFLOAD_SITES = ("offload_demote", "offload_promote")
SITES = (ENGINE_SITES + NET_SITES + MIGRATE_SITES + PROC_SITES
         + FRONTEND_SITES + OFFLOAD_SITES)


class FaultInjector:
    """Seeded, schedulable failure points.

    Parameters
    ----------
    seed : storm seed.  ``scheduled(site, tick)`` hashes
        ``(seed, site, tick)`` against ``rates[site]`` — a pure
        function, so the same seed always yields the same schedule.
    rates : dict site -> fire probability per (site, tick).  Sites
        absent from the dict never fire stochastically (explicit
        ``at()`` entries still do).
    hang_s : simulated d2h hang duration.  The hang is COOPERATIVE:
        it sleeps in small increments polling the engine's
        ``_watchdog_fired`` flag, so an armed watchdog converts it
        into a WatchdogTimeout raise mid-hang; without a watchdog it
        is just a bounded slow consume.
    slow_s : host_slow sleep per firing.
    first_tick / last_tick : stochastic firing window (inclusive;
        None = unbounded on that side).  A chaos storm bounds it so
        the engine warms up and drains to idle cleanly around the
        storm, leaving the invariants checkable — explicit ``at()``
        entries ignore the window.
    """

    def __init__(self, seed=0, rates=None, hang_s=0.05, slow_s=0.01,
                 blackhole_s=0.02, net_slow_s=0.005,
                 first_tick=None, last_tick=None):
        self.seed = int(seed)
        rates = dict(rates or {})
        unknown = set(rates) - set(SITES)
        if unknown:
            raise ValueError(
                f"unknown fault sites {sorted(unknown)}; known: {SITES}")
        self.rates = rates
        self.hang_s = float(hang_s)
        self.slow_s = float(slow_s)
        self.blackhole_s = float(blackhole_s)
        self.net_slow_s = float(net_slow_s)
        self.first_tick = first_tick
        self.last_tick = last_tick
        self._explicit = set()   # (site, tick) one-shot entries
        self.log = []            # fired (tick, site), in firing order

    def at(self, tick, site):
        """Schedule an explicit one-shot firing of ``site`` at engine
        ``tick`` (exempt from ``last_tick``).  Returns self."""
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r}")
        self._explicit.add((site, int(tick)))
        return self

    def _u01(self, site, tick):
        h = hashlib.blake2b(f"{self.seed}:{site}:{tick}".encode(),
                            digest_size=8).digest()
        return int.from_bytes(h, "big") / 2.0 ** 64

    def scheduled(self, site, tick):
        """Pure schedule query: does ``site`` fire at ``tick``?"""
        if (site, tick) in self._explicit:
            return True
        rate = self.rates.get(site, 0.0)
        if rate <= 0.0:
            return False
        if self.first_tick is not None and tick < self.first_tick:
            return False
        if self.last_tick is not None and tick > self.last_tick:
            return False
        return self._u01(site, tick) < rate

    def fire(self, site, tick, engine=None, emitted=None, abort=None,
             proc=None, arm=None):
        """Record the firing and perform the site's action (may raise;
        the record lands FIRST so the log is complete even for raising
        sites).  ``emitted``: the transport's tokens-received-so-far
        snapshot, attached to a ``net_disconnect`` raise so a failover
        can resume with context.  ``abort``: optional callable polled
        during the cooperative ``net_blackhole`` wait (a router that
        already declared this replica dead need not sit out the full
        simulated timeout).  ``proc``: the target replica's Popen-like
        handle for the ``proc_kill9`` / ``proc_stop`` sites (the storm
        driver owns the processes; without a handle the firing is
        record-only).  ``arm``: the storm driver's make-the-next-boots-
        fail hook for ``proc_crashloop``."""
        self.log.append((tick, site))
        if site == "dispatch":
            raise InjectedFault(
                f"injected dispatch failure at tick {tick}")
        if site == "pool_exhaust":
            from .kvcache import NoFreeBlocks
            raise NoFreeBlocks(
                f"injected pool exhaustion at tick {tick}")
        if site == "host_slow":
            time.sleep(self.slow_s)
            return
        if site == "d2h_hang":
            deadline = time.monotonic() + self.hang_s
            while time.monotonic() < deadline:
                if engine is not None and getattr(
                        engine, "_watchdog_fired", False):
                    raise WatchdogTimeout(
                        f"watchdog converted a wedged d2h at tick "
                        f"{tick} into step recovery")
                time.sleep(0.002)
            return
        if site == "spec_draft":
            raise InjectedFault(
                f"injected proposer failure at tick {tick}")
        if site == "net_refuse":
            raise NetRefused(
                f"injected connection refused at op {tick}")
        if site == "net_blackhole":
            # cooperative: poll the abort hook so a caller that has
            # other ways of learning the replica is dead (a probe
            # verdict) converts the black hole into an instant raise
            deadline = time.monotonic() + self.blackhole_s
            while time.monotonic() < deadline:
                if abort is not None and abort():
                    break
                time.sleep(0.002)
            raise NetTimeout(
                f"injected black hole at op {tick}: no response "
                f"within the simulated {self.blackhole_s * 1e3:.0f} ms "
                "client timeout")
        if site == "net_slow":
            time.sleep(self.net_slow_s)
            return
        if site == "net_disconnect":
            n = len(emitted or [])
            raise NetDisconnect(
                f"injected mid-body disconnect at op {tick} after "
                f"{n} emitted tokens", emitted=emitted)
        if site == "migrate_export":
            raise InjectedFault(
                f"injected export failure at tick {tick}: migration "
                "declined, the stream stays on the source")
        if site == "migrate_wire":
            raise NetDisconnect(
                f"injected wire loss at op {tick}: the migration "
                "payload vanished in flight", emitted=emitted)
        if site == "migrate_import":
            raise InjectedFault(
                f"injected import failure at tick {tick}: the "
                "destination adopted nothing")
        if site == "proc_kill9":
            # hard process death: the supervisor sees the exit on its
            # next sweep and restarts with backoff
            if proc is not None:
                try:
                    proc.send_signal(_signal.SIGKILL)
                except (ProcessLookupError, OSError):
                    pass  # already dead: the record still stands
            return
        if site == "proc_stop":
            # SIGSTOP wedge: the process stays alive (poll() is None)
            # but stops answering — only /livez timeouts reveal it
            if proc is not None:
                try:
                    proc.send_signal(_signal.SIGSTOP)
                except (ProcessLookupError, OSError):
                    pass
            return
        if site == "proc_crashloop":
            # exit-on-boot: the driver's hook arms the replica's next
            # restarts to fail immediately (httpd --fail-boot-below),
            # driving the supervisor's crash-loop quarantine
            if arm is not None:
                arm()
            return
        if site == "adapter_load":
            raise InjectedFault(
                f"injected adapter load/unload failure at tick {tick}: "
                "the demand fails, banks and inventory untouched")
        if site == "stream_disconnect":
            raise StreamDisconnect(
                f"injected streaming-client death at stream {tick}: "
                "the SSE consumer vanished mid-response")
        if site == "offload_demote":
            raise InjectedFault(
                f"injected demote failure at tick {tick}: the evicted "
                "block frees without spilling to the host tier")
        if site == "offload_promote":
            raise InjectedFault(
                f"injected promote failure at tick {tick}: admission "
                "falls back to recompute, host entries untouched")



class TickWatchdog:
    """Daemon thread converting a wedged engine tick into a recorded,
    observable failure.

    The engine stamps ``_tick_started_at`` on tick entry and clears it
    on exit; the watchdog polls the stamp and, when one tick exceeds
    ``timeout_s``:

    1. flight-records the in-flight state NOW (``Engine.last_flight``
       — the dump never materializes device futures, so a wedged
       dispatch cannot block it),
    2. sets ``engine._watchdog_fired`` so cooperative blocking points
       raise ``WatchdogTimeout`` into the step-failure recovery path,
    3. bumps ``serving.watchdog_fires``.

    It holds only a weakref: a collected engine ends the thread.  One
    firing per wedged tick (the flag clears at the next tick start).
    """

    def __init__(self, engine, timeout_s):
        self.timeout_s = float(timeout_s)
        if self.timeout_s <= 0:
            raise ValueError(
                f"watchdog timeout must be > 0, got {timeout_s}")
        self._engine = weakref.ref(engine)
        self._stop = threading.Event()
        self._fired_for = None   # tick id already handled
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="paddle_tpu-serving-watchdog")

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def _run(self):
        poll = max(self.timeout_s / 4.0, 0.002)
        while not self._stop.wait(poll):
            eng = self._engine()
            if eng is None:
                return
            started = eng._tick_started_at
            if started is None:
                continue
            tick = eng.tick_no
            if tick == self._fired_for:
                continue
            if time.monotonic() - started > self.timeout_s:
                self._fired_for = tick
                ms = round(self.timeout_s * 1e3, 1)
                exc = WatchdogTimeout(
                    f"tick {tick} exceeded the {ms} ms watchdog — "
                    "in-flight dispatch wedged")
                try:
                    eng._record_flight(exc)
                    eng._m_watchdog.inc()
                    eng.tracer.instant(
                        "engine.watchdog", cat="engine", tick=tick,
                        timeout_ms=round(self.timeout_s * 1e3, 3))
                except Exception:
                    pass  # the watchdog must never kill itself
                eng._watchdog_fired = True
