"""Hierarchical KV cache: the host-RAM offload tier.

Every capacity lever so far — paged pools (kvcache.py), int8 codes +
scales (quant.py), mesh-sharded pools — treats the device pool as the
ONLY home for computed K/V: when ``PrefixCache.evict`` fires under
pool pressure the blocks are simply freed, and a preempted or
finished stream's warm prefix past the trie is recomputed from
scratch.  Host RAM is ~10-50x HBM; this module turns those discards
into cheap restores by giving evicted blocks a second, much larger
tier:

* ``HostBlockStore`` — a capacity-bounded, byte-accounted,
  LRU-within-budget map from CONTENT ADDRESS to one block's host
  payload.  The address is the blake2b hash of the full token prefix
  the block's K/V encodes (``prefix_key``): the prefix trie's node
  identity flattened to a string, so two requests sharing a system
  prompt demote/promote the SAME entries (dedup is free) and a
  promote can trust the payload matches the prompt bytes it hashed.
  Geometry and dtype are checked like the migration wire
  (``import_blocks``): int8 codes must carry their scales
  (``KVDtypeMismatch`` otherwise), and a wrong block shape is refused
  before any byte is adopted.

* The DEMOTE path (engine-integrated, serving/engine.py): prefix-cache
  eviction — including the blocks preemption parked in the trie —
  fires ``PrefixCache``'s evict hook, which enqueues an async device
  gather of the dying block's rows *before* the pool ref drops.  The
  gather is dispatched immediately (jax arrays are immutable and
  device execution is in-order, so the snapshot is consistent even
  though later dispatches donate the pools) but MATERIALIZED at the
  next tick boundary (``Engine._service_offload``), double-buffered so
  the d2h copy hides behind the next dispatch instead of blocking the
  engine thread mid-tick.

* The PROMOTE path: the paged admission gate consults the device trie
  first, then this store — a host hit reserves fresh device blocks,
  scatters the payload back (``import_blocks``), seeds the device
  trie, and skips prefill for the restored span exactly like a device
  prefix hit (token-identical greedy AND seeded, proven against a
  never-evicted oracle in tests/test_offload.py).

Host-side only: nothing here touches a device array — the engine owns
the gathers/scatters, this module owns bytes, keys, and the LRU
budget.  Single-writer like the rest of the KV metadata (the engine
loop thread); ``stats()`` reads are snapshot-cheap for /healthz.

Fault sites (serving/faults.py ``OFFLOAD_SITES``): a scheduled
``offload_demote`` frees the block WITHOUT spilling (the store never
sees a partial entry), a scheduled ``offload_promote`` falls back to
recompute (the fresh device blocks stay plain prefill targets) —
neither tier is ever corrupted.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict

import numpy as np

from .kvcache import KVDtypeMismatch


def prefix_key(tokens, n_tokens=None):
    """Content address of the KV block whose trie node covers token
    positions ``[n - block_size, n)`` of ``tokens`` — the blake2b hash
    of the FULL prefix ``tokens[:n]`` (``n = n_tokens`` or all of
    ``tokens``).  Hashing the whole prefix, not just the block's own
    span, is what makes the address a content address: a block's K/V
    depends on every token before it, so two blocks are interchangeable
    iff their full prefixes match — exactly the prefix trie's node
    identity, flattened."""
    arr = np.asarray(tokens, np.int32)
    if n_tokens is not None:
        arr = arr[:int(n_tokens)]
    return hashlib.blake2b(arr.tobytes(), digest_size=16).hexdigest()


class HostBlockStore:
    """Capacity-bounded host-RAM tier for demoted KV blocks.

    One entry per content address (``prefix_key``): the block's K/V
    rows for every layer as ONE numpy array ``(n_layers, 2,
    block_size, num_heads, head_dim)`` (axis 1 = K, V — the
    per-block slice of ``kvcache.export_blocks``' layout), plus, for
    int8 pools, the parallel per-head scales ``(n_layers, 2,
    num_heads)``.  Entries are byte-accounted (codes + scales both
    count) and evicted LRU-within-budget on ``put`` — the store never
    exceeds ``capacity_mb``.

    Geometry/dtype discipline mirrors the migration wire: the store is
    constructed with the engine's block geometry and kv dtype label,
    ``put`` refuses a mismatched payload (``KVDtypeMismatch`` for the
    quantization disagreement, ``ValueError`` for shape) so a bug can
    never park garbage that a later promote would scatter into live
    pools.

    Single-writer (the engine loop thread) like BlockPool/PrefixCache;
    ``stats()`` is safe to read from handler threads (plain int
    fields)."""

    def __init__(self, capacity_mb, block_size, num_heads, head_dim,
                 n_layers, dtype="float32"):
        capacity_mb = float(capacity_mb)
        if capacity_mb <= 0:
            raise ValueError(
                f"kv_host_mb must be > 0, got {capacity_mb:g}")
        self.capacity_bytes = int(capacity_mb * 2 ** 20)
        self.capacity_mb = capacity_mb
        self.block_size = int(block_size)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.n_layers = int(n_layers)
        self.dtype = str(dtype)
        self.quant = self.dtype == "int8"
        # the expected per-entry shapes, fixed at construction like
        # the migration wire's `want` geometry
        self._want = (self.n_layers, 2, self.block_size,
                      self.num_heads, self.head_dim)
        self._want_scales = (self.n_layers, 2, self.num_heads)
        self._entries = OrderedDict()  # key -> (data, scales|None)
        self.bytes_used = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.refusals = 0    # oversize puts turned away
        self.dedup_puts = 0  # puts whose key was already resident

    def __len__(self):
        return len(self._entries)

    def __contains__(self, key):
        """Presence probe WITHOUT touching the LRU order (the
        admission gate probes every continuation block before it
        commits to a restore — probes must not age out colder
        entries' recency)."""
        return key in self._entries

    @staticmethod
    def _nbytes(data, scales):
        return int(data.nbytes) + (int(scales.nbytes)
                                   if scales is not None else 0)

    def _check(self, data, scales):
        if self.quant and scales is None:
            raise KVDtypeMismatch(
                "host store holds int8 blocks (kv_dtype='int8') but "
                "the demoted payload carries no scales — refusing to "
                "park fp rows in a quantized tier")
        if not self.quant and scales is not None:
            raise KVDtypeMismatch(
                "demoted payload carries int8 codes + scales but the "
                "host store is fp (kv_dtype mismatch) — refusing")
        if tuple(data.shape) != self._want:
            raise ValueError(
                f"demoted block shape {tuple(data.shape)} does not "
                f"match the store geometry (want {self._want}: layers "
                "x (K,V) x block_size x heads x head_dim)")
        if scales is not None \
                and tuple(scales.shape) != self._want_scales:
            raise ValueError(
                f"demoted scale shape {tuple(scales.shape)} does not "
                f"match the store geometry (want {self._want_scales}: "
                "layers x (K,V) x heads)")

    def put(self, key, data, scales=None):
        """Park one demoted block under its content address.  Returns
        True when the entry is resident afterwards (including the
        dedup case — the key was already stored, its recency just
        refreshes: same prefix means same content, re-copying would
        buy nothing), False when the entry alone exceeds the whole
        budget (refused; the block simply frees, like a failed
        demote).  Evicts LRU entries until the budget holds.  Raises
        on geometry/dtype mismatch — see ``_check``."""
        data = np.asarray(data)
        if scales is not None:
            scales = np.asarray(scales)
        self._check(data, scales)
        if key in self._entries:
            self._entries.move_to_end(key)
            self.dedup_puts += 1
            return True
        nb = self._nbytes(data, scales)
        if nb > self.capacity_bytes:
            self.refusals += 1
            return False
        while self.bytes_used + nb > self.capacity_bytes:
            _, (d, s) = self._entries.popitem(last=False)
            self.bytes_used -= self._nbytes(d, s)
            self.evictions += 1
        # own copies: the caller's arrays may be views over a larger
        # materialized gather it is about to drop
        self._entries[key] = (np.ascontiguousarray(data),
                              None if scales is None
                              else np.ascontiguousarray(scales))
        self.bytes_used += nb
        return True

    def get(self, key):
        """The entry for ``key`` as ``(data, scales)`` — ``scales`` is
        None for fp stores — or None on a miss.  A hit refreshes the
        entry's LRU recency (a promoted prefix is warm again)."""
        ent = self._entries.get(key)
        if ent is None:
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        return ent

    def discard(self, key):
        """Drop one entry (returns True if it existed)."""
        ent = self._entries.pop(key, None)
        if ent is None:
            return False
        self.bytes_used -= self._nbytes(*ent)
        return True

    def clear(self):
        """Drop every entry (engine teardown); returns how many."""
        n = len(self._entries)
        self._entries = OrderedDict()
        self.bytes_used = 0
        return n

    def keys(self):
        """Resident content addresses, LRU-oldest first (tests +
        debug surfaces)."""
        return list(self._entries)

    def stats(self):
        """JSON-able snapshot for /healthz and /debug/requests."""
        return {
            "blocks": len(self._entries),
            "bytes": self.bytes_used,
            "capacity_mb": self.capacity_mb,
            "dtype": self.dtype,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "refusals": self.refusals,
            "dedup_puts": self.dedup_puts,
        }
