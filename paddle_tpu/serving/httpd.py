"""Thin stdlib HTTP endpoint for smoke-serving an Engine.

Not a production frontend — it exists so the engine can be driven and
scraped end-to-end with nothing but ``curl`` (and so tests exercise the
full submit -> queue -> slot -> result path over a real socket):

  POST /generate   {"prompt": [1,2,3], "max_new_tokens": 8,
                    "eos_token_id": null, "timeout": null,
                    "temperature": 1.0, "top_k": 0, "top_p": 1.0,
                    "priority": 0, "tenant": null,
                    "adapter": null, "stream": false}
                -> {"ids": [...], "generated": [...], "ttft_ms": ...}
                   overload: 503 QueueFull / DeadlineShed, 429
                   RateLimited — each with a COMPUTED Retry-After
                   (queue backlog over the measured drain rate /
                   token-bucket refill time), not a fixed constant.
                   "adapter" routes through a loaded LoRA lane (404
                   {"reason": "unknown_adapter"} otherwise).
                   "stream": true switches the response to SSE
                   (text/event-stream, no buffering): one "token"
                   event per generated token the tick it lands,
                   ":hb" comment frames on idle gaps, and a terminal
                   "done" event carrying the full /generate payload
                   — or a terminal "error" event with the reason and
                   retry_after when the stream is shed or dies
                   mid-response (the client never sees a silently
                   truncated body)
  GET  /metrics    Prometheus text exposition (monitor registry)
  GET  /healthz    {"slots_free": n, "queue_depth": n,
                    "kv_blocks_free": n|null, ...} — always carries
                   the router-tier load signals (queue depth, free
                   slots, free KV blocks) plus the LIVENESS vs
                   READINESS split: "live" (process up), "ready"
                   (accepting new work), "state" distinguishing
                   "draining" (finishing up — stop routing, let it
                   land its streams) from "watchdog_fired" (wedged
                   tick — possibly dying) from "ok"
  GET  /livez      200 while the process serves (liveness probe)
  GET  /readyz     200 {"ready": true} when accepting new work;
                   503 with a machine-readable "reason"
                   ("draining" | "watchdog_fired") when not — a
                   router (or k8s-style prober) distinguishes
                   "dying" from "finishing up" without parsing prose
  GET  /debug/trace     current trace ring as chrome-trace JSON
                        (open in chrome://tracing / Perfetto, or feed
                        tools/trace_view.py)
  GET  /debug/requests  in-flight slot/request states (prefill
                        progress, spec lanes, KV blocks) + the queue
                        + the recent migration log
  POST /migrate/export  KV block migration, source side.  Three body
                        shapes: {"request_id": n} exports a LIVE
                        stream; {"prompt": [...], ...generate params,
                        "min_tokens": 1} submits, decodes to
                        min_tokens, then exports (the disaggregated
                        PREFILL replica's path); {"prefix_only":
                        true, "tokens": [...]} exports the longest
                        cached prefix from the trie (cross-replica
                        prefix warming).  -> {"completed": bool,
                        "generated": [...], "payload": {...}|null}
                        with the payload in JSON wire form
                        (kvcache.payload_to_json)
  POST /migrate/import  destination side: body is a wire payload.  A
                        stream payload is adopted block-for-block and
                        DECODED TO COMPLETION here — the response is
                        /generate-shaped (the disaggregated DECODE
                        replica's path); a prefix payload ("request"
                        null) warms the trie -> {"blocks": n,
                        "tokens": n}.  Failure leaves the destination
                        owning nothing (503 "migrate_failed" /
                        "queue_full"; 400 on geometry mismatch)

Every 4xx/5xx body is JSON with a machine-readable ``reason``
(``bad_request`` / ``queue_full`` / ``rate_limited`` /
``deadline_shed`` / ``draining`` / ``result_timeout`` / ``internal``
/ ``not_found`` / ``http_<code>`` for stdlib-generated errors) and a
``Content-Type`` header — the router tier's retry classifier keys on
``reason``, never on prose.

Handlers run on ThreadingHTTPServer worker threads and block on
``Request.result()`` while the engine's own thread decodes — the
continuous-batching point: N concurrent POSTs share slot ticks.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import monitor
from .kvcache import KVDtypeMismatch, payload_to_json
from .lora import UnknownAdapter
from .request import (DeadlineShed, RateLimited, Rejected,
                      RequestTimeout)
from .stream import TokenStream, sse_format


def _retry_after_header(e):
    """Retry-After header dict from a Rejected exception's computed
    hint (HTTP wants integer delta-seconds; round up, floor 1).
    ``retry_after=None`` means the engine has NO honest backoff —
    e.g. an over-burst request that can never pass its rate limit —
    so no header is sent rather than a made-up constant that would
    put a compliant client on a retry treadmill."""
    ra = getattr(e, "retry_after", None)
    if ra is None:
        return {}
    return {"Retry-After": str(max(int(-(-float(ra) // 1)), 1))}


def _hist_mean(h):
    """Mean of a monitor Histogram as a rounded float, 0.0 when
    missing or empty (the /healthz JSON must never carry a NaN)."""
    return 0.0 if h is None else round(h.mean(), 3)


def _shed_reason(e, draining=False):
    """Machine-readable reason code for a Rejected exception — the
    router's retry classifier keys on this, not on the message."""
    if isinstance(e, RateLimited):
        return "rate_limited"
    if isinstance(e, DeadlineShed):
        return "deadline_shed"
    # QueueFull covers both a full queue and a draining engine; the
    # distinction matters to a router (draining = stop routing here
    # entirely; queue_full = back off and retry here) — the caller
    # passes the engine's actual drain flag, never message prose
    if draining:
        return "draining"
    return "queue_full"


def _readiness(eng):
    """(ready, state) for the liveness/readiness split: an engine that
    is DRAINING is finishing up (in-flight streams complete, no new
    work), one whose WATCHDOG fired is wedged mid-tick (possibly
    dying) — a prober must treat the two differently, and neither is
    the same as dead."""
    if getattr(eng, "_watchdog_fired", False):
        return False, "watchdog_fired"
    if getattr(eng, "_draining", False):
        return False, "draining"
    return True, "ok"


class JsonHandler(BaseHTTPRequestHandler):
    """Shared JSON-HTTP plumbing for the serving tier's handlers
    (engine httpd AND routerd): quiet logging, Content-Length'd
    sends, and the JSON-with-``reason`` error contract — including
    stdlib-generated errors (malformed request line, unsupported
    method), which would otherwise emit an HTML body.  The contract
    lives HERE, once: a router client never parses prose."""

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _send(self, code, body, ctype="application/json", headers=None):
        data = body if isinstance(body, bytes) else body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code, obj, headers=None):
        self._send(code, json.dumps(obj), headers=headers)

    def send_error(self, code, message=None, explain=None):
        # stdlib send_error closes the connection — keep that: a
        # stdlib-generated error (unsupported method, malformed
        # request line) can leave an unread request body on the
        # socket, and a keep-alive client would desync parsing those
        # bytes as the next request line
        self.close_connection = True
        body = json.dumps({"error": message or f"HTTP {code}",
                           "reason": f"http_{code}"}).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.send_header("Connection", "close")
        self.end_headers()
        # stdlib suppresses the body for HEAD and bodyless statuses
        if self.command != "HEAD" and code >= 200 \
                and code not in (204, 304):
            self.wfile.write(body)


class _Handler(JsonHandler):
    engine = None          # bound per-server via the factory below
    result_timeout = 120.0
    engine_server = None   # owning EngineServer (drain relay; the
    #   name "server" is taken — BaseHTTPRequestHandler binds it)
    incarnation = 0        # supervisor restart generation (/healthz)
    role = "mixed"         # disaggregation role advertised on
    #   /healthz: "prefill" / "decode" / "mixed" — purely a routing
    #   signal (every endpoint works on every role; the router's
    #   phase filter is what specializes the replicas)

    def _validate_prompt(self, prompt, max_new_tokens):
        """Reject malformed / over-capacity prompts AT THE EDGE with a
        clear 400 body, instead of letting them surface as an
        engine-side failure or a silently-clamped embedding gather.
        Returns an error string, or None when the request is
        admissible."""
        eng = self.engine
        if not isinstance(prompt, (list, tuple)) or not prompt:
            return "prompt must be a non-empty list of token ids"
        if not all(isinstance(t, int) and not isinstance(t, bool)
                   for t in prompt):
            return "prompt must contain integer token ids only"
        if max_new_tokens < 1:
            return f"max_new_tokens must be >= 1, got {max_new_tokens}"
        # mirrors Engine.submit's capacity rule (kept in sync with it):
        # checking here too means a clear 400 with zero engine-side
        # effects, not an error minted halfway into submit
        total = len(prompt) + max_new_tokens
        if total > eng.max_seq_len:
            return (f"prompt ({len(prompt)} tokens) + max_new_tokens "
                    f"({max_new_tokens}) = {total} exceeds the engine's "
                    f"slot capacity ({eng.max_seq_len})")
        vocab = getattr(eng, "vocab_size", None)
        if vocab:
            bad = next((t for t in prompt if not 0 <= t < vocab), None)
            if bad is not None:
                return (f"token id {bad} outside the model vocabulary "
                        f"[0, {vocab}) — it would silently clamp to a "
                        "different token")
        return None

    def do_GET(self):
        eng = self.engine
        if self.path == "/metrics":
            # the full exposition content type: scrapers negotiate on
            # the version/charset params, not just text/plain
            self._send(200, monitor.render_prometheus(eng.registry),
                       ctype="text/plain; version=0.0.4; charset=utf-8")
        elif self.path == "/healthz":
            # queue_depth / slots_free / kv_blocks_free are ALWAYS
            # present: they are the per-engine load signals a router
            # tier balances on (kv_blocks_free is null in contiguous
            # mode — capacity there is slots, not blocks)
            ready, state = _readiness(eng)
            info = {
                "status": "ok",
                # liveness vs readiness: answering at all = live;
                # ready only when accepting new work; state carries
                # WHY not ("draining" vs "watchdog_fired")
                "live": True,
                "ready": ready,
                "state": state,
                "watchdog_fired": bool(
                    getattr(eng, "_watchdog_fired", False)),
                "slots_total": eng.num_slots,
                "slots_free": eng.scheduler.free_count(),
                "queue_depth": eng.queue.depth(),
                "kv_blocks_free": (
                    eng.block_pool.free_count()
                    if getattr(eng, "_paged", False) else None),
                # the router's prefix-affinity hash aligns on this
                "kv_block_size": (eng._bs if getattr(eng, "_paged",
                                                     False) else None),
                "sample_mode": getattr(eng, "sample_mode", "host"),
                # disaggregated serving: which phase this replica
                # volunteers for (the router's pick() filters on it)
                "role": self.role,
                # which attention implementation serves the paged
                # dispatches: "ragged" = the Pallas ragged paged
                # attention kernel in its streaming online-softmax
                # form (one program for decode / spec / chunk
                # windows, O(block_size x window) working set),
                # "ragged_gather" = the materialize-the-row A/B
                # reference, "xla" = the per-shape gather/scatter
                # programs (the CPU parity oracle); the router copies
                # this into its registry signals like kv_dtype
                "attn_impl": getattr(eng, "attn_impl", "xla"),
                # long-context exposure: max context length (prompt +
                # decoded) any request has reached on this replica
                "max_context_len": getattr(
                    eng, "_max_context_len", 0),
                # mesh surface: the router registry carries these so
                # a fleet view (and timeline.py --router) can label
                # sharded replicas with the full (mp, dp) shape; kv
                # blocks are head-sliced UNIFORMLY across mp shards
                # (same logical free count on each), while dp shards
                # own DISJOINT slot/block ranges and can drain
                # independently — so the free list enumerates each dp
                # shard's own count, repeated per mp shard
                "mesh_shape": getattr(eng, "mesh_axes", None),
                "mp": getattr(eng, "mp", 1),
                "dp": getattr(eng, "dp", 1),
                "kv_blocks_free_per_shard": (
                    [eng.block_pool.free_count(d)
                     for d in range(getattr(eng, "dp", 1))]
                    * getattr(eng, "mp", 1)
                    if getattr(eng, "_paged", False) else None),
                "kv_block_bytes_per_shard": getattr(
                    eng, "_kv_block_bytes_per_shard", None),
                # quantized serving (serving/quant.py): dtype labels
                # plus the code/scale byte split, so the capacity
                # accounting adds up (code + scale = block bytes) and
                # a migration source can refuse a kv_dtype-mismatched
                # peer BEFORE shipping blocks it would reject
                "weight_dtype": getattr(eng, "_weight_dtype_str",
                                        None),
                "kv_dtype": getattr(eng, "_kv_dtype_str", None),
                "kv_block_bytes": getattr(
                    eng, "_kv_code_bytes_per_shard", None),
                "kv_scale_bytes": getattr(
                    eng, "_kv_scale_bytes_per_shard", None),
                # async-loop signals, next to the router-tier load
                # signals: pipeline depth plus the mean overlapped
                # host time and mean blocking d2h wait per tick —
                # overlap >> wait means the loop is hiding its host
                # work behind device compute
                "async_depth": getattr(eng, "async_depth", 1),
                "tick_overlap_ms": _hist_mean(
                    getattr(eng, "_m_overlap", None)),
                "d2h_wait_ms": _hist_mean(
                    getattr(eng, "_m_d2h_wait", None)),
                # multi-adapter serving: the loaded inventory is a
                # ROUTING signal — the router's pick() filters
                # replicas on it for model= requests
                "adapters": (
                    eng.adapters.names()
                    if getattr(eng, "adapters", None) is not None
                    else []),
                "adapters_loaded": (
                    len(eng.adapters)
                    if getattr(eng, "adapters", None) is not None
                    else 0),
                "streams_active": (
                    eng.streams_active()
                    if hasattr(eng, "streams_active") else 0),
            }
            # overload-protection signals: preemption / shed counts,
            # the measured drain rate behind Retry-After estimates,
            # and the graceful-drain / watchdog state
            def _cnt(name):
                m = getattr(eng, name, None)
                return 0 if m is None else int(m.value)
            # ONE drain_rate() read: the staleness horizon means a
            # second call can flip to None between two reads
            rate = getattr(eng, "drain_rate", lambda: None)()
            info.update({
                "preemptions_total": _cnt("_m_preempt"),
                "resumed_total": _cnt("_m_resumed"),
                "shed_deadline_total": _cnt("_m_shed_deadline"),
                "shed_rate_limited_total": _cnt("_m_shed_rate"),
                "shed_queue_full_total": _cnt("_m_shed_queue"),
                "watchdog_fires": _cnt("_m_watchdog"),
                "drain_rate_tps": (None if rate is None
                                   else round(rate, 1)),
                "draining": bool(getattr(eng, "_draining", False)),
                # restart generation stamped by the supervisor tier:
                # the router registry resets a replica's breaker and
                # health history when this advances, and DISCARDS any
                # probe carrying a lower value (a stale read from the
                # dead predecessor on the same URL)
                "incarnation": int(getattr(self, "incarnation", 0)),
            })
            srv = getattr(self, "engine_server", None)
            if srv is not None:
                info["drain_migrations_total"] = int(
                    srv._m_drain_migrations.value)
                info["drain_fallbacks_total"] = int(
                    srv._m_drain_fallbacks.value)
            if getattr(eng, "_paged", False):
                info["kv_blocks_cached"] = (
                    eng.prefix_cache.cached_blocks()
                    if eng.prefix_cache is not None else 0)
            store = getattr(eng, "host_store", None)
            if store is not None:
                # host-RAM offload tier: warmth the router's
                # prefix_warm can prefer over a peer's recompute
                st = store.stats()
                info["kv_host_blocks"] = st["blocks"]
                info["kv_host_bytes"] = st["bytes"]
                info["kv_host_capacity_mb"] = st["capacity_mb"]
                info["offload_demotes_total"] = _cnt(
                    "_m_offload_demotes")
                info["offload_promotes_total"] = _cnt(
                    "_m_offload_promotes")
                info["offload_hit_tokens_total"] = _cnt(
                    "_m_offload_hit_tokens")
            if getattr(eng, "_spec_k", None):
                info["spec_k"] = eng._spec_k
                info["spec_acceptance_rate"] = round(
                    eng._m_spec_rate.value, 4)
                info["spec_tokens_per_tick"] = round(
                    eng._m_spec_tpt.value, 4)
            self._send_json(200, info)
        elif self.path == "/livez":
            # liveness only: the process is up and answering — a
            # draining or wedged engine is still LIVE (restarting it
            # would kill the streams it is trying to land)
            self._send_json(200, {"status": "ok", "live": True})
        elif self.path == "/readyz":
            ready, state = _readiness(eng)
            if ready:
                self._send_json(200, {"status": "ok", "ready": True,
                                      "state": state})
            else:
                # 503 so a dumb prober can act on the status code
                # alone; "reason" so a smart one can distinguish
                # draining (finishing up) from watchdog_fired (dying)
                self._send_json(503, {"status": "unavailable",
                                      "ready": False, "state": state,
                                      "reason": state})
        elif self.path == "/debug/trace":
            # the live trace ring as a downloadable chrome-trace file
            self._send(
                200, json.dumps(eng.chrome_trace()),
                headers={"Content-Disposition":
                         'attachment; filename="trace.json"'})
        elif self.path == "/debug/requests":
            self._send_json(200, eng.debug_requests())
        else:
            self._send_json(404, {"error": f"no route {self.path}",
                                  "reason": "not_found"})

    def do_POST(self):
        if self.path == "/migrate/export":
            self._migrate_export()
            return
        if self.path == "/migrate/import":
            self._migrate_import()
            return
        if self.path != "/generate":
            self._send_json(404, {"error": f"no route {self.path}",
                                  "reason": "not_found"})
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
            prompt = body["prompt"]
            max_new = int(body.get("max_new_tokens", 16))
        except (KeyError, TypeError, ValueError,
                json.JSONDecodeError) as e:
            self._send_json(400, {"error": f"bad request: {e}",
                                  "reason": "bad_request"})
            return
        err = self._validate_prompt(prompt, max_new)
        if err is not None:
            self._send_json(400, {"error": err,
                                  "reason": "bad_request"})
            return
        try:
            req = self.engine.submit(
                prompt,
                max_new_tokens=max_new,
                eos_token_id=body.get("eos_token_id"),
                timeout=body.get("timeout"),
                temperature=float(body.get("temperature", 1.0)),
                top_k=int(body.get("top_k", 0)),
                top_p=float(body.get("top_p", 1.0)),
                seed=body.get("seed"),
                priority=int(body.get("priority", 0)),
                tenant=body.get("tenant"),
                adapter=body.get("adapter"))
        except UnknownAdapter as e:
            # 404, not 400: the request is well-formed — THIS replica
            # lacks the adapter.  The router retries elsewhere on it.
            self._send_json(404, {"error": str(e),
                                  "reason": "unknown_adapter"})
            return
        except Rejected as e:
            # every shed (QueueFull / DeadlineShed 503, RateLimited
            # 429) carries the engine's COMPUTED backoff: queue
            # backlog over the measured drain rate, or the token
            # bucket's refill time — an honest hint, not a constant
            code = 429 if isinstance(e, RateLimited) else 503
            self._send_json(
                code,
                {"error": str(e),
                 "reason": _shed_reason(e, draining=bool(
                     getattr(self.engine, "_draining", False)))},
                headers=_retry_after_header(e))
            return
        except (TypeError, ValueError) as e:
            # TypeError covers JSON nulls / non-numeric fields hitting
            # the int()/float() coercions — still a 400, not a dropped
            # connection
            self._send_json(400, {"error": str(e),
                                  "reason": "bad_request"})
            return
        if body.get("stream"):
            self._stream_response(req)
            return
        try:
            ids = req.result(timeout=self.result_timeout)
        except RequestTimeout as e:
            self._send_json(504, {"error": str(e),
                                  "reason": "result_timeout"})
            return
        except (TimeoutError, RuntimeError) as e:
            srv = getattr(self, "engine_server", None)
            if srv is not None:
                # lazy: only engine-ful processes reach this branch
                from .engine import Migrated
                if isinstance(e, Migrated):
                    # a SIGTERM drain exported this stream mid-decode:
                    # the drain thread is landing it on a peer and
                    # relays the peer's COMPLETE response back here —
                    # the client never learns its stream moved hosts
                    found, resp = srv.await_relay(
                        req.id, timeout=self.result_timeout)
                    if found and resp is not None:
                        out = dict(resp)
                        out["migrated"] = True
                        self._send_json(200, out)
                        return
                    if found:
                        # the drain tried and no peer accepted:
                        # retryable — the router re-dispatches from
                        # the prompt (greedy resume, token-identical)
                        self._send_json(
                            503, {"error": str(e),
                                  "reason": "drain_failed"})
                        return
            self._send_json(500, {"error": str(e),
                                  "reason": "internal"})
            return
        ttft = None
        if req.first_token_at is not None:
            ttft = round((req.first_token_at - req.submitted_at) * 1e3, 3)
        self._send_json(200, {
            "id": req.id,
            "ids": [int(x) for x in ids],
            "generated": [int(x) for x in req.generated],
            "ttft_ms": ttft,
        })

    # -- SSE streaming (POST /generate {"stream": true}) ---------------
    def _stream_response(self, req):
        """Server half of token streaming: headers out immediately
        (text/event-stream, no Content-Length, proxy buffering off),
        then one ``token`` event per generated token the tick the
        engine emits it — the handler thread drains the request's
        TokenStream sink while the engine thread decodes.  Idle gaps
        emit ``:hb`` comment frames (keep-alive + dead-client
        detection).  The stream ALWAYS ends with a terminal event:
        ``done`` carrying the full /generate payload, or ``error``
        with the machine-readable reason and retry_after — a shed or
        preempt-timeout mid-stream is an honest terminal frame, never
        a silently truncated body.  A SIGTERM-drain migration is
        SPLICED: the peer's relayed tokens beyond what was already
        streamed continue the same SSE stream seamlessly."""
        stream = TokenStream(req, heartbeat_s=0.25)
        self.close_connection = True  # the frame has no length; it
        #   ends when the connection does
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("X-Accel-Buffering", "no")
        self.send_header("Connection", "close")
        self.end_headers()
        deadline = time.monotonic() + self.result_timeout
        sent = 0
        try:
            for ev in stream:
                if ev.kind == "token":
                    self.wfile.write(sse_format(
                        {"token": int(ev.token),
                         "index": int(ev.index)}, event="token"))
                    sent += 1
                elif ev.kind == "heartbeat":
                    if time.monotonic() > deadline:
                        self.wfile.write(sse_format(
                            {"error": "no terminal event before "
                             "result_timeout",
                             "reason": "result_timeout",
                             "retry_after": None}, event="error"))
                        return
                    self.wfile.write(sse_format(comment="hb"))
                elif ev.kind == "done":
                    ttft = None
                    if req.first_token_at is not None:
                        ttft = round((req.first_token_at
                                      - req.submitted_at) * 1e3, 3)
                    self.wfile.write(sse_format({
                        "id": req.id,
                        "ids": [int(t) for t in req.prompt]
                        + [int(t) for t in req.generated],
                        "generated": [int(t) for t in req.generated],
                        "ttft_ms": ttft, "streamed": sent,
                    }, event="done"))
                    return
                else:
                    self._stream_error(req, ev.error, sent)
                    return
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            # the client vanished mid-stream: nothing to answer; the
            # engine lands the request and this sink dies with the
            # handler thread
            pass

    def _stream_error(self, req, err, sent):
        """Terminal frame for a stream that did not finish cleanly.
        Migrated + a draining EngineServer is the one recoverable
        case: await the drain relay and SPLICE the peer's completion
        into the live stream (tokens beyond ``sent`` — the ones this
        socket has not yet delivered — then ``done``)."""
        from .engine import Migrated
        srv = getattr(self, "engine_server", None)
        if isinstance(err, Migrated) and srv is not None:
            found, resp = srv.await_relay(req.id,
                                          timeout=self.result_timeout)
            if found and resp is not None:
                gen = [int(t) for t in resp.get("generated", [])]
                for j in range(sent, len(gen)):
                    self.wfile.write(sse_format(
                        {"token": gen[j], "index": j}, event="token"))
                out = dict(resp)
                out["migrated"] = True
                out["streamed"] = sent + max(len(gen) - sent, 0)
                self.wfile.write(sse_format(out, event="done"))
                return
            self.wfile.write(sse_format(
                {"error": str(err),
                 "reason": "drain_failed" if found else "internal",
                 "retry_after": None}, event="error"))
            return
        if isinstance(err, RequestTimeout):
            reason = "result_timeout"
        elif isinstance(err, Rejected):
            reason = _shed_reason(err, draining=bool(
                getattr(self.engine, "_draining", False)))
        else:
            reason = "internal"
        self.wfile.write(sse_format(
            {"error": str(err), "reason": reason,
             "retry_after": getattr(err, "retry_after", None)},
            event="error"))

    def _read_body(self):
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n) or b"{}")

    def _migrate_export(self):
        """Source side of a migration.  The disaggregated-prefill
        shape submits here, lets the engine decode to ``min_tokens``
        (so the destination resumes a DECODING stream through the
        proven preemption-resume binding), then exports."""
        eng = self.engine
        try:
            body = self._read_body()
        except (TypeError, ValueError, json.JSONDecodeError) as e:
            self._send_json(400, {"error": f"bad request: {e}",
                                  "reason": "bad_request"})
            return
        try:
            if body.get("prefix_only"):
                payload = eng.export_prefix(
                    body.get("tokens") or [],
                    timeout=self.result_timeout)
                self._send_json(200, {
                    "completed": False, "generated": [],
                    "payload": (None if payload is None
                                else payload_to_json(payload))})
                return
            if "request_id" in body:
                res = eng.migrate_out(
                    request_id=int(body["request_id"]),
                    min_tokens=int(body.get("min_tokens", 1)),
                    deliver="return", timeout=self.result_timeout)
            else:
                prompt = body.get("prompt")
                max_new = int(body.get("max_new_tokens", 16))
                err = self._validate_prompt(prompt, max_new)
                if err is not None:
                    self._send_json(400, {"error": err,
                                          "reason": "bad_request"})
                    return
                req = eng.submit(
                    prompt, max_new_tokens=max_new,
                    eos_token_id=body.get("eos_token_id"),
                    timeout=body.get("timeout"),
                    temperature=float(body.get("temperature", 1.0)),
                    top_k=int(body.get("top_k", 0)),
                    top_p=float(body.get("top_p", 1.0)),
                    seed=body.get("seed"),
                    priority=int(body.get("priority", 0)),
                    tenant=body.get("tenant"))
                res = eng.migrate_out(
                    request_id=req.id,
                    min_tokens=int(body.get("min_tokens", 1)),
                    deliver="return", timeout=self.result_timeout)
        except Rejected as e:
            code = 429 if isinstance(e, RateLimited) else 503
            self._send_json(
                code,
                {"error": str(e),
                 "reason": _shed_reason(e, draining=bool(
                     getattr(eng, "_draining", False)))},
                headers=_retry_after_header(e))
            return
        except KeyError as e:
            self._send_json(404, {"error": str(e),
                                  "reason": "not_found"})
            return
        except TimeoutError as e:
            self._send_json(504, {"error": str(e),
                                  "reason": "result_timeout"})
            return
        except (TypeError, ValueError) as e:
            self._send_json(400, {"error": str(e),
                                  "reason": "bad_request"})
            return
        except Exception as e:  # injected export fault: the stream
            #   (if any) keeps running HERE — a retryable decline
            self._send_json(503, {"error": str(e),
                                  "reason": "migrate_declined"})
            return
        payload = res.get("payload")
        self._send_json(200, {
            "completed": bool(res.get("completed")),
            "generated": [int(t) for t in res.get("generated") or []],
            "payload": (None if payload is None
                        else payload_to_json(payload))})

    def _migrate_import(self):
        """Destination side.  A stream payload is adopted and decoded
        to completion — the response is /generate-shaped, with the
        pre-migration tokens included, so the caller (router) streams
        one complete answer.  A prefix payload only warms the trie.
        Every failure path leaves this replica owning nothing."""
        eng = self.engine
        try:
            body = self._read_body()
        except (TypeError, ValueError, json.JSONDecodeError) as e:
            self._send_json(400, {"error": f"bad request: {e}",
                                  "reason": "bad_request"})
            return
        try:
            if body.get("request") is None:
                res = eng.import_prefix(body,
                                        timeout=self.result_timeout)
                self._send_json(200, {"blocks": res["blocks"],
                                      "tokens": res["tokens"]})
                return
            res = eng.migrate_in(body, timeout=self.result_timeout)
        except Rejected as e:
            code = 429 if isinstance(e, RateLimited) else 503
            self._send_json(
                code,
                {"error": str(e),
                 "reason": _shed_reason(e, draining=bool(
                     getattr(eng, "_draining", False)))},
                headers=_retry_after_header(e))
            return
        except TimeoutError as e:
            self._send_json(504, {"error": str(e),
                                  "reason": "result_timeout"})
            return
        except KVDtypeMismatch as e:
            # quantized/fp peers disagree on the wire kv dtype: the
            # payload is fine, THIS pairing is wrong — a distinct
            # machine-readable reason so the sender can filter peers
            # by the /healthz kv_dtype signal instead of retrying
            self._send_json(400, {"error": str(e),
                                  "reason": "kv_dtype_mismatch"})
            return
        except (TypeError, ValueError) as e:
            # malformed payload / geometry mismatch: re-sending the
            # same bytes here cannot succeed — non-retryable 400
            self._send_json(400, {"error": str(e),
                                  "reason": "bad_request"})
            return
        except Exception as e:  # injected import fault / pool
            #   exhaustion: this replica adopted NOTHING, the payload
            #   holder may import elsewhere — retryable 503
            self._send_json(503, {"error": str(e),
                                  "reason": "migrate_failed"})
            return
        req = res["request"]
        try:
            ids = req.result(timeout=self.result_timeout)
        except RequestTimeout as e:
            self._send_json(504, {"error": str(e),
                                  "reason": "result_timeout"})
            return
        except (TimeoutError, RuntimeError) as e:
            self._send_json(500, {"error": str(e),
                                  "reason": "internal"})
            return
        ttft = None
        if req.first_token_at is not None:
            ttft = round((req.first_token_at - req.submitted_at) * 1e3,
                         3)
        self._send_json(200, {
            "id": req.id,
            "ids": [int(x) for x in ids],
            "generated": [int(x) for x in req.generated],
            "ttft_ms": ttft,
            "migrated_blocks": res["blocks"],
        })


class EngineServer:
    """Engine tick loop + ThreadingHTTPServer, each on its own daemon
    thread.  ``with EngineServer(engine) as srv: ... srv.port``.

    ``incarnation`` is this process's restart generation, stamped by
    the supervisor tier (``serving.supervisor``) and advertised on
    ``/healthz`` — the router registry keys its breaker/health reset
    on it so a dead process's stale probes never poison its successor.
    ``peers`` are sibling replica base URLs: on SIGTERM (or an
    explicit ``drain_to_peers()``), the server flips ``/readyz`` to
    draining and migrates every live decoding stream to the first
    healthy peer over the ``/migrate/import`` wire, relaying the
    peer's completed response back to the stream's still-blocked
    ``/generate`` waiter — a supervised rolling restart loses zero
    tokens.  When no peer accepts, the waiter gets a retryable 503
    ``drain_failed`` and the router's greedy resume covers it."""

    def __init__(self, engine, host="127.0.0.1", port=0,
                 result_timeout=120.0, role="mixed", incarnation=0,
                 peers=(), drain_grace_s=30.0):
        if role not in ("mixed", "prefill", "decode"):
            raise ValueError(f"role must be 'mixed', 'prefill' or "
                             f"'decode', got {role!r}")
        self.engine = engine
        self.role = role
        self.incarnation = int(incarnation)
        self.peers = [str(u).rstrip("/") for u in (peers or ())]
        self.drain_grace_s = float(drain_grace_s)
        # drain relay: request id -> the peer's completed /generate
        # response (None = no peer accepted); the /generate handler
        # that caught Migrated consumes its entry
        self._relay = {}
        self._relay_cv = threading.Condition()
        self._drain_active = False
        self._m_drain_migrations = engine.registry.counter(
            "supervisor.drain_migrations",
            "live streams migrated to a peer during a SIGTERM drain")
        self._m_drain_fallbacks = engine.registry.counter(
            "supervisor.drain_fallbacks",
            "drain streams no peer accepted (router greedy resume)")
        handler = type("BoundHandler", (_Handler,),
                       {"engine": engine,
                        "result_timeout": float(result_timeout),
                        "role": role,
                        "incarnation": self.incarnation})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        # bound AFTER construction: the handler type must exist before
        # the server, the server before self is complete (the name
        # "server" is taken — BaseHTTPRequestHandler binds it to the
        # ThreadingHTTPServer per request)
        handler.engine_server = self
        self.host, self.port = self.httpd.server_address[:2]
        self._http_thread = None

    @property
    def address(self):
        return f"http://{self.host}:{self.port}"

    # -- SIGTERM drain -------------------------------------------------
    def _post_relay(self, rid, resp):
        with self._relay_cv:
            self._relay[rid] = resp
            self._relay_cv.notify_all()

    def await_relay(self, rid, timeout=30.0):
        """Called by a ``/generate`` handler whose request ended in
        ``Migrated``: wait for the drain thread to finish shipping the
        stream and return ``(found, resp)``.  ``found`` False means no
        drain owns this request (a non-drain migration — the caller
        keeps its legacy 500 path); resp None means the drain tried
        and no peer accepted."""
        deadline = time.monotonic() + float(timeout)
        with self._relay_cv:
            while rid not in self._relay:
                if not self._drain_active:
                    return False, None
                left = deadline - time.monotonic()
                if left <= 0:
                    return False, None
                self._relay_cv.wait(min(left, 0.1))
            resp = self._relay.pop(rid)
            self._relay_cv.notify_all()   # the drain's consumed-wait
            return True, resp

    def _peer_ready(self, url, timeout=2.0):
        import urllib.request
        try:
            with urllib.request.urlopen(url + "/readyz",
                                        timeout=timeout):
                return True
        except Exception:
            return False

    def _post_json(self, url, obj, timeout=60.0):
        import urllib.request
        data = json.dumps(obj).encode()
        req = urllib.request.Request(
            url, data=data,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())

    def drain_to_peers(self, peers=None, grace_s=None):
        """Graceful recycling: flip readiness to draining, export
        every live decoding stream and land it on a healthy peer via
        the KV-migration wire, relay each peer's completed response
        to the stream's blocked ``/generate`` waiter, and return the
        accounting ``{"migrated", "fallback", "lost_tokens",
        "peers"}``.  ``lost_tokens`` counts tokens already emitted on
        streams NO peer accepted (those wait-listed for the router's
        greedy re-decode) — a drain with healthy peers reports 0.
        The engine keeps ticking throughout (mid-prefill streams
        become exportable a few ticks in); whatever is still live at
        ``grace_s`` falls to the engine's own graceful stop."""
        eng = self.engine
        urls = [str(u).rstrip("/") for u in
                (self.peers if peers is None else peers)]
        grace = (self.drain_grace_s if grace_s is None
                 else float(grace_s))
        with self._relay_cv:
            self._drain_active = True
        eng._draining = True      # /readyz -> 503 draining; submit
        #   sheds; the queue admits nothing more
        healthy = [u for u in urls if self._peer_ready(u)]
        migrated = fallback = lost = 0
        deadline = time.monotonic() + grace
        try:
            while time.monotonic() < deadline:
                live = eng.live_request_ids()
                if not live:
                    break
                rid = live[0]
                try:
                    res = eng.migrate_out(
                        request_id=rid, min_tokens=1,
                        deliver="return",
                        timeout=min(5.0, max(
                            0.1, deadline - time.monotonic())))
                except TimeoutError:
                    continue   # not decoding yet — tick on
                except KeyError:
                    continue   # finished between snapshot and export
                except Exception:
                    continue   # export declined: the stream keeps
                    #   running and the engine's stop drain lands it
                if res.get("completed") or res.get("payload") is None:
                    continue   # finished during export — the waiter
                    #   already has its complete result
                gen = [int(t) for t in res.get("generated") or []]
                resp = None
                with eng.tracer.span("drain.migrate", cat="serving",
                                     request=rid, tokens=len(gen)):
                    wire = payload_to_json(res["payload"])
                    for u in healthy:
                        try:
                            resp = self._post_json(
                                u + "/migrate/import", wire)
                            break
                        except Exception:
                            continue
                if resp is not None:
                    migrated += 1
                    self._m_drain_migrations.inc()
                    self._post_relay(rid, resp)
                else:
                    fallback += 1
                    lost += len(gen)
                    self._m_drain_fallbacks.inc()
                    self._post_relay(rid, None)
            # let the blocked waiters consume their relays before the
            # server goes down (handler threads are daemons: nothing
            # else waits for them)
            waited = time.monotonic() + 5.0
            with self._relay_cv:
                while self._relay and time.monotonic() < waited:
                    self._relay_cv.wait(0.1)
        finally:
            with self._relay_cv:
                self._drain_active = False
                self._relay_cv.notify_all()
        return {"migrated": migrated, "fallback": fallback,
                "lost_tokens": lost, "peers": healthy}

    def start(self):
        self.engine.start()
        self._http_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name="paddle_tpu-serving-http")
        self._http_thread.start()
        return self

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout=5.0)
            self._http_thread = None
        self.engine.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False


def serve(engine, host="127.0.0.1", port=8000, result_timeout=120.0):
    """Blocking convenience: start the engine and serve HTTP until
    KeyboardInterrupt."""
    srv = EngineServer(engine, host=host, port=port,
                       result_timeout=result_timeout).start()
    try:
        srv._http_thread.join()
    except KeyboardInterrupt:
        pass
    finally:
        srv.close()


def main(argv=None):
    """Standalone replica process: build a GPT config, optionally
    shard it over an mp-degree mesh, and serve — what
    ``distributed.launch.spawn_serving_fleet`` spawns N of (one
    process per replica, each replica itself mesh-sharded over its
    own device pool).

        python -m paddle_tpu.serving.httpd --config tiny --mp 2 \\
            --port 8000 --kv-block-size 8

    ``--seed`` makes every replica of a fleet initialize IDENTICAL
    weights, so greedy failover across replicas is token-identical
    (the fleet tests and bench assert it).  ``--mp > 1`` needs that
    many devices — on CPU the launcher forces a virtual pool via
    XLA_FLAGS (per-worker env propagation is its job)."""
    import argparse

    p = argparse.ArgumentParser("paddle_tpu.serving.httpd")
    p.add_argument("--config", default="tiny",
                   help="GPT_CONFIGS name (models/gpt.py)")
    p.add_argument("--mp", type=int, default=1,
                   help="tensor-parallel degree: shard the model + KV"
                        " pools over a mesh of this many devices")
    p.add_argument("--dp", type=int, default=1,
                   help="data-parallel degree: shard batch slots (and"
                        " their KV block ranges) over this many mesh"
                        " rows — total devices = mp * dp")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--seed", type=int, default=0,
                   help="weight-init seed (same seed across a fleet "
                        "= token-identical replicas)")
    p.add_argument("--num-slots", type=int, default=4)
    p.add_argument("--max-seq-len", type=int, default=64)
    p.add_argument("--kv-block-size", type=int, default=None)
    p.add_argument("--kv-blocks", type=int, default=None)
    p.add_argument("--kv-budget-mb", type=float, default=None)
    p.add_argument("--prefill-chunk", type=int, default=None)
    p.add_argument("--spec-k", type=int, default=None)
    p.add_argument("--result-timeout", type=float, default=120.0)
    p.add_argument("--role", default="mixed",
                   choices=("mixed", "prefill", "decode"),
                   help="disaggregation role advertised on /healthz: "
                        "the router routes new prompts to prefill "
                        "replicas and migrated streams to decode "
                        "replicas (every endpoint still works on "
                        "every role)")
    p.add_argument("--incarnation", type=int, default=0,
                   help="restart generation stamped by the "
                        "supervisor: advertised on /healthz so the "
                        "router can reset breaker/health state and "
                        "discard stale probes from the predecessor")
    p.add_argument("--peer", action="append", default=[],
                   metavar="URL",
                   help="sibling replica base URL (repeatable): the "
                        "SIGTERM drain migrates live streams to the "
                        "first healthy peer")
    p.add_argument("--drain-grace", type=float, default=30.0,
                   help="seconds the SIGTERM drain may spend "
                        "migrating live streams before exiting")
    p.add_argument("--fail-boot-below", type=int, default=None,
                   metavar="N",
                   help="chaos: exit(23) at boot while incarnation "
                        "< N — the proc_crashloop fault site; the "
                        "supervisor's crash-loop breaker quarantines "
                        "the replica")
    args = p.parse_args(argv)

    if (args.fail_boot_below is not None
            and args.incarnation < args.fail_boot_below):
        # the proc_crashloop site: die BEFORE the heavy model imports
        # so the crash loop is fast enough to trip the supervisor's
        # window, exactly like a bad binary rollout would
        import sys
        print(f"crashloop: incarnation {args.incarnation} < "
              f"{args.fail_boot_below}, failing boot", flush=True)
        sys.exit(23)

    import signal as _signal

    import paddle_tpu as paddle
    from ..models.gpt import GPTModel
    from .engine import Engine

    paddle.seed(args.seed)
    model = GPTModel.from_config(args.config, dropout=0.0)
    model.eval()
    mesh = None
    if args.mp > 1 or args.dp > 1:
        if args.mp > 1:
            model = model.to_tensor_parallel()
        mesh = (args.mp, args.dp)
    engine = Engine(model, num_slots=args.num_slots,
                    max_seq_len=args.max_seq_len,
                    kv_block_size=args.kv_block_size,
                    kv_blocks=args.kv_blocks,
                    kv_budget_mb=args.kv_budget_mb,
                    prefill_chunk=args.prefill_chunk,
                    spec_k=args.spec_k, mesh=mesh)
    # graceful recycling: SIGTERM sets a flag the main thread acts on
    # (the handler itself must stay trivial — it can interrupt a tick)
    stop_evt = threading.Event()
    try:
        _signal.signal(_signal.SIGTERM, lambda s, f: stop_evt.set())
    except ValueError:
        pass   # not the main thread (embedded use): no drain hook
    # the port line is the launcher's readiness handshake: printed
    # AFTER the socket is bound, flushed so a pipe reader sees it
    srv = EngineServer(engine, host=args.host, port=args.port,
                       result_timeout=args.result_timeout,
                       role=args.role, incarnation=args.incarnation,
                       peers=args.peer,
                       drain_grace_s=args.drain_grace).start()
    print(f"serving {args.config} mp={args.mp} dp={args.dp} "
          f"on {srv.address}", flush=True)
    try:
        while not stop_evt.wait(0.2):
            if not srv._http_thread.is_alive():
                break
    except KeyboardInterrupt:
        pass
    try:
        if stop_evt.is_set():
            acct = srv.drain_to_peers()
            # the supervisor/bench parse this accounting line from
            # the replica log: a rolling restart must report 0 lost
            print("drain: migrated={migrated} fallback={fallback} "
                  "lost_tokens={lost_tokens}".format(**acct),
                  flush=True)
    finally:
        srv.close()


if __name__ == "__main__":
    main()
