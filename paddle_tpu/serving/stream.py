"""Token streaming: per-tick emission fanned out to caller threads.

The engine already emits token-by-token — ``_emit`` runs once per
generated token inside the tick loop — but every front-end so far
buffered the whole response before answering.  ``TokenStream`` closes
that gap: it is a thread-safe sink a handler thread ATTACHES to a live
``Request``; attachment atomically replays the tokens already emitted
(attach races the engine, so replay-then-subscribe under the request's
sink lock is what makes delivery exactly-once) and then receives every
subsequent token the moment ``_emit`` records it.  The terminal event
carries the request's outcome — normal completion, or the engine error
(shed, preempt-timeout, migrated) the HTTP edge turns into a terminal
SSE ``error`` event instead of a silently truncated body.

Iteration yields ``StreamEvent``s; with ``heartbeat_s`` set, quiet
gaps yield ``heartbeat`` events so an SSE writer can emit keep-alive
comments and detect dead clients between tokens.

Chaos integration: the ``stream_disconnect`` fault site (same pure
(seed, site, tick) schedule as every other site) simulates a client
vanishing mid-response — ``TokenStream(faults=..., ordinal=n)`` aborts
iteration with ``StreamDisconnect`` after a schedule-derived number of
tokens, which is exactly what a TCP reset mid-SSE looks like to the
server loop.
"""
from __future__ import annotations

import queue
import time


class StreamClosed(Exception):
    """Iterating past the terminal event (the stream is over)."""


class StreamEvent:
    """One streamed occurrence.

    kind : "token" | "heartbeat" | "done" | "error"
    token/index : the generated id and its 0-based position (token)
    error : the request's failure (error kind)
    t : monotonic emission timestamp (client-side TTFT measurements)
    """

    __slots__ = ("kind", "token", "index", "error", "t")

    def __init__(self, kind, token=None, index=None, error=None):
        self.kind = kind
        self.token = token
        self.index = index
        self.error = error
        self.t = time.monotonic()

    def __repr__(self):
        if self.kind == "token":
            return f"StreamEvent(token={self.token}, i={self.index})"
        return f"StreamEvent({self.kind}, error={self.error!r})"


class TokenStream:
    """A consumer-side token stream over one ``Request``.

    Typical use (an HTTP handler thread)::

        req = engine.submit(prompt, max_new_tokens=64)
        for ev in TokenStream(req, heartbeat_s=0.5):
            if ev.kind == "token":
                write_sse(ev.token)
            elif ev.kind == "heartbeat":
                write_sse_comment()
        # terminal "done"/"error" ends iteration; .error holds failure

    The stream buffers internally, so a slow client never back-
    pressures the engine thread — ``feed`` is a lock-free Queue.put.
    """

    def __init__(self, req=None, heartbeat_s=None, faults=None,
                 ordinal=0):
        self._q = queue.Queue()
        self.heartbeat_s = heartbeat_s
        self.error = None
        self.closed = False
        self.tokens = []          # every token this stream delivered
        self.first_token_t = None  # client-side TTFT anchor
        self._disconnect_after = None
        self._faults = faults
        self._ordinal = int(ordinal)
        if faults is not None and faults.scheduled("stream_disconnect",
                                                   self._ordinal):
            # deterministic mid-response client kill: vanish after a
            # schedule-derived number of tokens (>= 1 so the stream is
            # genuinely mid-body, not refused)
            self._disconnect_after = 1 + self._ordinal % 3
        if req is not None:
            self.attach(req)

    # -- producer side (engine / request) --------------------------------
    def attach(self, req):
        """Subscribe to ``req``: replay already-emitted tokens, then
        receive the rest live — atomic under the request's sink lock,
        so no token is ever lost or duplicated."""
        with req._sink_lock:
            for i, tok in enumerate(req.generated):
                self._q.put(StreamEvent("token", token=tok, index=i))
            if req._done.is_set():
                self._q.put(StreamEvent(
                    "error" if req.error is not None else "done",
                    error=req.error))
            else:
                req._sinks.append(self)
        return self

    def feed(self, tok, index):
        self._q.put(StreamEvent("token", token=tok, index=index))

    def close(self, error=None):
        self._q.put(StreamEvent(
            "error" if error is not None else "done", error=error))

    # -- consumer side ----------------------------------------------------
    def __iter__(self):
        while not self.closed:
            try:
                ev = self._q.get(timeout=self.heartbeat_s)
            except queue.Empty:
                yield StreamEvent("heartbeat")
                continue
            if ev.kind == "token":
                if self.first_token_t is None:
                    self.first_token_t = ev.t
                if (self._disconnect_after is not None
                        and len(self.tokens) >= self._disconnect_after):
                    # the scheduled client kill: log through the
                    # injector (chaos forensics) and vanish
                    self.closed = True
                    self._faults.fire("stream_disconnect",
                                      self._ordinal)
                self.tokens.append(ev.token)
            else:
                self.closed = True
                self.error = ev.error
            yield ev

    def drain(self, timeout=None):
        """Consume to the terminal event; returns the delivered token
        list.  Raises the stream's error, mirroring
        ``Request.result``."""
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        for ev in self:
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"stream: no terminal event after {timeout}s")
        if self.error is not None:
            raise self.error
        return list(self.tokens)


def sse_format(data=None, event=None, comment=None):
    """Serialize one SSE frame (bytes).  ``data`` may be any
    JSON-serializable value; ``comment`` renders a ``:``-prefixed
    keep-alive line (ignored by EventSource clients)."""
    import json
    out = []
    if comment is not None:
        out.append(f": {comment}")
    if event is not None:
        out.append(f"event: {event}")
    if data is not None:
        out.append("data: " + json.dumps(data))
    return ("\n".join(out) + "\n\n").encode()


def parse_sse(line_iter):
    """Incremental SSE parser over an iterator of raw lines (bytes or
    str, newline-stripped or not) — the client half ``sse_format`` is
    the server half of.  Yields (event, data_str) per frame; comments
    and blank keep-alives are skipped.  Used by the router's HTTP
    transport to follow a replica's stream token-by-token."""
    event, data = None, []
    for raw in line_iter:
        line = raw.decode() if isinstance(raw, bytes) else raw
        line = line.rstrip("\r\n")
        if not line:               # frame boundary
            if data:
                yield (event or "message", "\n".join(data))
            event, data = None, []
            continue
        if line.startswith(":"):
            continue               # keep-alive comment
        if line.startswith("event:"):
            event = line[6:].strip()
        elif line.startswith("data:"):
            data.append(line[5:].lstrip())
    if data:                       # unterminated final frame
        yield (event or "message", "\n".join(data))
