"""JIT / static-graph path.

Reference parity: ``@paddle.jit.to_static`` (dygraph_to_static AST rewriting
+ ProgramTranslator, ``fluid/dygraph/jit.py:160``) and ``jit.save/load``
(TranslatedLayer).

TPU-native design: there is no AST rewriting and no ProgramDesc — a Layer's
``forward`` is already traceable because ops accept tracers.  ``to_static``
wraps forward in ``jax.jit`` via ``functional_call`` (parameters become
traced inputs, so one compiled program serves every step without retracing);
``jit.save`` exports the traced computation as a serialized StableHLO
artifact plus a pickled state dict; ``jit.load`` rehydrates a
TranslatedLayer that runs the compiled artifact.
"""
from __future__ import annotations

import contextlib
import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..core import autograd, rng
from ..core.dispatch import primitive
from ..nn.layer.base import Layer


# -- functional bridge ----------------------------------------------------
def named_params_and_buffers(layer: Layer):
    params = dict(layer.named_parameters())
    buffers = {k: v for k, v in layer.named_buffers() if v is not None}
    return params, buffers


@contextlib.contextmanager
def _swapped(tensors: dict, arrays: dict):
    """Temporarily rebind Tensor storage to (possibly traced) arrays."""
    saved = {}
    try:
        for name, arr in arrays.items():
            t = tensors[name]
            saved[name] = t._data
            t._data = arr
        yield
    finally:
        for name, old in saved.items():
            tensors[name]._data = old


def functional_call(layer: Layer, param_arrays: dict, buffer_arrays: dict,
                    args, kwargs=None, training=None, rng_key=None):
    """Run layer.forward as a pure function of (params, buffers, inputs).

    Returns (outputs_pytree_of_arrays, new_buffer_arrays).  Buffer mutation
    (BN running stats) during the call is captured and returned functionally.
    """
    kwargs = kwargs or {}
    params, buffers = named_params_and_buffers(layer)
    prev_training = layer.training
    if training is not None:
        (layer.train() if training else layer.eval())
    if rng_key is not None:
        rng.push_trace_key(rng_key)
    try:
        with _swapped(params, param_arrays), \
                _swapped(buffers, buffer_arrays):
            wrapped = [Tensor(a, stop_gradient=True) if isinstance(
                a, (jnp.ndarray, jax.Array)) or hasattr(a, "aval") else a
                for a in args]
            out = layer.forward(*wrapped, **kwargs)
            new_buffers = {k: buffers[k]._data for k in buffer_arrays}
    finally:
        if rng_key is not None:
            rng.pop_trace_key()
        if training is not None:
            (layer.train() if prev_training else layer.eval())
    return _unwrap_tree(out), new_buffers


def _unwrap_tree(out):
    if isinstance(out, Tensor):
        return out._data
    if isinstance(out, (list, tuple)):
        return type(out)(_unwrap_tree(o) for o in out)
    if isinstance(out, dict):
        return {k: _unwrap_tree(v) for k, v in out.items()}
    return out


def _wrap_tree(out, stop_gradient=True):
    if isinstance(out, (jnp.ndarray, jax.Array)) or hasattr(out, "aval"):
        return Tensor(out, stop_gradient=stop_gradient)
    if isinstance(out, (list, tuple)):
        return type(out)(_wrap_tree(o, stop_gradient) for o in out)
    if isinstance(out, dict):
        return {k: _wrap_tree(v, stop_gradient) for k, v in out.items()}
    return out


# -- to_static ------------------------------------------------------------
class StaticFunction:
    """Compiled callable over a Layer's forward (or a plain function)."""

    def __init__(self, function, input_spec=None):
        if isinstance(function, Layer):
            self._layer = function
            self._fn = None
        elif hasattr(function, "__self__") and isinstance(
                function.__self__, Layer):
            self._layer = function.__self__
            self._fn = None
        else:
            self._layer = None
            self._fn = function
        self._input_spec = input_spec
        self._cache = {}
        self.forward = self.__call__
        # AST control-flow conversion (reference: dygraph_to_static
        # program_translator + ifelse/loop transformers): rewrite tensor-
        # dependent if/while into converter calls.  Semantics-preserving
        # eagerly, so the converted forward replaces the original for both
        # modes; tracing stays the fallback when there is nothing to
        # convert or the source is unavailable.
        from . import dy2static as _d2s
        if self._layer is not None:
            fwd = type(self._layer).forward
            if not getattr(fwd, "__wrapped_by_dy2static__", False):
                conv = _d2s.convert_function(fwd)
                if conv is not None:
                    self._layer.forward = conv.__get__(self._layer)
        elif self._fn is not None and not getattr(
                self._fn, "__wrapped_by_dy2static__", False):
            conv = _d2s.convert_function(self._fn)
            if conv is not None:
                self._fn = conv

    def _key(self, arrays, training):
        return tuple((tuple(a.shape), str(a.dtype)) for a in arrays) + (
            training,)

    def __call__(self, *args, **kwargs):
        if not ProgramTranslator._enabled:
            # ProgramTranslator().enable(False): run the original dygraph
            # code uncompiled (reference: program_translator.py enable)
            if self._layer is not None:
                return self._layer.forward(*args, **kwargs)
            return self._fn(*args, **kwargs)
        try:
            if self._layer is None:
                return self._call_function(*args, **kwargs)
            return self._call_layer(*args, **kwargs)
        except (jax.errors.ConcretizationTypeError,
                jax.errors.TracerArrayConversionError) as e:
            # every tracer->host concretization failure: bool/int paths
            # subclass ConcretizationTypeError; the numpy()/__array__
            # path (which Tensor.__bool__ funnels through) raises
            # TracerArrayConversionError, a sibling in jax's hierarchy.
            # The reference rewrites such code via AST transforms; the
            # TPU build asks for explicit structured control flow.
            if isinstance(e, jax.errors.TracerArrayConversionError):
                detail = ("converts a Tensor to a host value "
                          "(numpy()/item()/bool()) mid-trace — often a "
                          "Python `if`/`while` on a Tensor's value")
            else:
                detail = ("uses a Tensor's VALUE in Python control flow "
                          "(`if`/`while`/`range`/indexing)")
            raise TypeError(
                f"@to_static: this forward {detail}, which cannot be "
                "traced. For value-dependent control flow use "
                "paddle.static.nn.cond / while_loop (lowered to "
                "lax.cond/lax.while_loop); remove stray host conversions "
                "from the compiled path; or debug eagerly via "
                "paddle.jit.enable_to_static(False). "
                "(reference: dygraph_to_static AST transformers)") from e

    # plain function path
    def _call_function(self, *args, **kwargs):
        arrays = [a._data if isinstance(a, Tensor) else jnp.asarray(a)
                  for a in args]
        key = self._key(arrays, None)
        if key not in self._cache:
            fn = self._fn

            @jax.jit
            def pure(*arrs):
                with autograd.no_grad():
                    out = fn(*[Tensor(a) for a in arrs], **kwargs)
                return _unwrap_tree(out)

            self._cache[key] = pure
        return _wrap_tree(self._cache[key](*arrays))

    # layer path: params are traced args → grads flow via the tape
    def _call_layer(self, *args, **kwargs):
        layer = self._layer
        params, buffers = named_params_and_buffers(layer)
        pnames = sorted(params)
        bnames = sorted(buffers)
        arrays = [a._data if isinstance(a, Tensor) else jnp.asarray(a)
                  for a in args]
        training = layer.training
        key = self._key(arrays, training) + (tuple(pnames), tuple(bnames))
        seed_key = rng.next_key() if training else jax.random.key(0)
        if key not in self._cache:
            n_p, n_b = len(pnames), len(bnames)

            @jax.jit
            def pure(seed, p_arrs, b_arrs, in_arrs):
                with autograd.no_grad():
                    out, new_buf = functional_call(
                        layer, dict(zip(pnames, p_arrs)),
                        dict(zip(bnames, b_arrs)), in_arrs, kwargs,
                        training=training, rng_key=seed)
                return out, [new_buf[k] for k in bnames]

            self._cache[key] = pure
        pure = self._cache[key]

        p_tensors = [params[k] for k in pnames]

        @primitive(name="static_function", has_aux=True)
        def run(*all_arrays):
            p_arrs = list(all_arrays[:len(pnames)])
            in_arrs = list(all_arrays[len(pnames):])
            out, new_bufs = pure(seed_key, p_arrs,
                                 [buffers[k]._data for k in bnames],
                                 in_arrs)
            return out, new_bufs

        res = run(*p_tensors, *[Tensor(a) for a in arrays])
        # split diff outputs from aux buffer outputs
        if isinstance(res, tuple):
            n_buf = len(bnames)
            outs = res[:len(res) - n_buf] if n_buf else res
            bufs = res[len(res) - n_buf:] if n_buf else ()
        else:
            outs, bufs = (res,), ()
        for name, b in zip(bnames, bufs):
            buffers[name]._data = b._data
        if isinstance(outs, tuple) and len(outs) == 1:
            return outs[0]
        return outs

    @property
    def code(self):
        return "<compiled by jax.jit (no AST transform needed on TPU)>"

    def concrete_program(self):
        return None


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """paddle.jit.to_static — decorator or call."""

    def deco(fn):
        return StaticFunction(fn, input_spec)

    if function is None:
        return deco
    return deco(function)


declarative = to_static


# -- save / load ----------------------------------------------------------
def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save — StableHLO export + state pickle.

    Produces `path.pdmodel` (serialized StableHLO for eval-mode forward) and
    `path.pdiparams` (pickled state dict) — same artifact split as the
    reference (reference: fluid/dygraph/jit.py save → __model__ + params).
    """
    from .. import framework
    from ..static import InputSpec

    if isinstance(layer, StaticFunction):
        layer = layer._layer
    if input_spec is None:
        raise ValueError("jit.save requires input_spec on TPU "
                         "(shapes define the compiled program)")
    # Dynamic dims (None/-1) export as shape polymorphism (jax.export
    # symbolic shapes) — matching the reference's batch-polymorphic
    # save_inference_model.  Axis 0 shares one "batch" symbol across all
    # inputs (paired feeds almost always co-vary there); other dynamic
    # axes get independent symbols.
    scope = None
    if any(isinstance(s, InputSpec)
           and any(d is None or (isinstance(d, int) and d < 0)
                   for d in s.shape)
           for s in input_spec):
        scope = jax.export.SymbolicScope()
    specs = []
    for i, spec in enumerate(input_spec):
        if isinstance(spec, InputSpec):
            dyn = [d is None or (isinstance(d, int) and d < 0)
                   for d in spec.shape]
            if any(dyn):
                parts = []
                for j, (d, is_dyn) in enumerate(zip(spec.shape, dyn)):
                    if not is_dyn:
                        parts.append(str(int(d)))
                    elif j == 0:
                        parts.append("batch")
                    else:
                        parts.append(f"dyn{i}_{j}")
                shape = jax.export.symbolic_shape(
                    ", ".join(parts), scope=scope)
            else:
                shape = tuple(int(s) for s in spec.shape)
            specs.append(jax.ShapeDtypeStruct(
                tuple(shape), jnp.dtype(spec.dtype)))
        elif isinstance(spec, Tensor):
            specs.append(jax.ShapeDtypeStruct(tuple(spec.shape),
                                              spec._data.dtype))
        else:
            specs.append(spec)

    params, buffers = named_params_and_buffers(layer)
    pnames, bnames = sorted(params), sorted(buffers)

    def pure(p_arrs, b_arrs, in_arrs):
        with autograd.no_grad():
            out, _ = functional_call(layer, dict(zip(pnames, p_arrs)),
                                     dict(zip(bnames, b_arrs)),
                                     in_arrs, {}, training=False,
                                     rng_key=None)
        return out

    jitted = jax.jit(pure)
    p_shapes = [jax.ShapeDtypeStruct(tuple(params[k].shape),
                                     params[k]._data.dtype) for k in pnames]
    b_shapes = [jax.ShapeDtypeStruct(tuple(buffers[k].shape),
                                     buffers[k]._data.dtype) for k in bnames]
    try:
        exported = jax.export.export(jitted)(p_shapes, b_shapes, specs)
    except Exception as e:
        if scope is not None:
            raise RuntimeError(
                f"{e}\n[paddle_tpu] export with dynamic dims failed while "
                "tracing with symbolic shapes — if the model's control "
                "flow or reshapes need concrete sizes, pass fully "
                "concrete shapes in input_spec (each batch size compiles "
                "separately at load time)") from e
        raise
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    state = {
        "params": {k: params[k].numpy() for k in pnames},
        "buffers": {k: buffers[k].numpy() for k in bnames},
        "pnames": pnames, "bnames": bnames,
        "input_specs": [([d if isinstance(d, int) else str(d)
                          for d in s.shape], str(s.dtype))
                        for s in specs],
    }
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(state, f, protocol=4)
    meta = {
        "kind": "jit",
        "feed_names": [getattr(s, "name", None) or f"x{i}"
                       for i, s in enumerate(input_spec)],
        "feed_specs": [([d if isinstance(d, int) else str(d)
                         for d in s.shape], str(s.dtype))
                       for s in specs],
        "n_fetch": len(exported.out_avals),
    }
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump(meta, f, protocol=4)


class TranslatedLayer(Layer):
    """paddle.jit.load result — runs an exported StableHLO program."""

    def __init__(self, exported, state):
        super().__init__()
        self._exported = exported
        self._state = state
        self._p_arrays = [jnp.asarray(state["params"][k])
                          for k in state["pnames"]]
        self._b_arrays = [jnp.asarray(state["buffers"][k])
                          for k in state["bnames"]]
        for k in state["pnames"]:
            self.add_parameter(k, Parameter(state["params"][k]))

    def forward(self, *args):
        arrays = [a._data if isinstance(a, Tensor) else jnp.asarray(a)
                  for a in args]
        out = self._exported.call(self._p_arrays, self._b_arrays,
                                  list(arrays))
        return _wrap_tree(out)


def load(path, **configs):
    with open(path + ".pdmodel", "rb") as f:
        exported = jax.export.deserialize(f.read())
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    return TranslatedLayer(exported, state)


def not_to_static(fn):
    return fn


class ProgramTranslator:
    """reference: dygraph_to_static/program_translator.py:756.

    The TPU build has no AST rewriting — jax tracing handles Python
    control flow via lax primitives (see static.nn.cond/while_loop) — so
    the translator reduces to a global enable/disable switch for
    to_static, mirroring ``ProgramTranslator().enable(False)`` usage.
    """

    _instance = None
    _enabled = True

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    @classmethod
    def get_instance(cls):
        return cls()

    def enable(self, enable_to_static=True):
        ProgramTranslator._enabled = bool(enable_to_static)

    @property
    def enable_to_static(self):
        return ProgramTranslator._enabled


def enable_to_static(flag=True):
    ProgramTranslator().enable(flag)


class TracedLayer:
    """reference: fluid/dygraph/jit.py TracedLayer — trace(layer, inputs)
    returns a compiled callable + save_inference_model."""

    def __init__(self, layer, inputs):
        self._layer = layer
        self._static = StaticFunction(layer)
        self._example = inputs

    @classmethod
    def trace(cls, layer, inputs):
        traced = cls(layer, inputs)
        outs = traced(*inputs)
        return (outs if isinstance(outs, (list, tuple)) else [outs],
                traced)

    def __call__(self, *args):
        return self._static(*args)

    def save_inference_model(self, path, feed=None, fetch=None, **kwargs):
        if feed is not None or fetch is not None:
            raise NotImplementedError(
                "TracedLayer.save_inference_model: feed/fetch subset "
                "selection is not supported — the full traced signature "
                "is exported")
        save(self._layer, path, input_spec=list(self._example))


# -- dygraph_to_static logging shims --------------------------------------
# reference: fluid/dygraph/dygraph_to_static/logging_utils.py:182,221 —
# verbosity/code-level logging for the AST transformer pipeline.  The TPU
# build has no AST transformers (tracing is native), so these configure a
# plain logger for trace diagnostics.
import logging as _logging

_D2S_LOGGER = _logging.getLogger("paddle_tpu.jit")


def set_verbosity(level=0, also_to_stdout=False):
    """reference: logging_utils.set_verbosity."""
    _D2S_LOGGER.setLevel(max(_logging.ERROR - int(level) * 10,
                             _logging.DEBUG))
    if also_to_stdout and not _D2S_LOGGER.handlers:
        _D2S_LOGGER.addHandler(_logging.StreamHandler())


def set_code_level(level=100, also_to_stdout=False):
    """reference: logging_utils.set_code_level — in the reference this
    prints transformed source per AST pass; there is no transformed code
    here, so it toggles trace-cache diagnostics instead."""
    set_verbosity(level if level < 100 else 9, also_to_stdout)


# `paddle.jit.dy2static` namespace: the real AST-conversion module
# (convert_ifelse/convert_while_loop/convert_logical_* + convert_function)
from . import dy2static  # noqa: E402,F401
