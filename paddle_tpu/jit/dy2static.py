"""dygraph→static AST conversion: tensor-dependent Python control flow.

Reference parity: ``fluid/dygraph/dygraph_to_static/`` — the AST
transformer pipeline (``program_translator.py:756``; ifelse_transformer,
loop_transformer, logical_transformer).  The reference rewrites ``if``/
``while``/``and``/``or``/``not`` into ``convert_ifelse``/
``convert_while_loop``/``convert_logical_*`` calls that dispatch on
whether the condition is a Variable.

TPU-native design: same two-stage shape — an ``ast.NodeTransformer``
rewrites the decorated function once, and the runtime converters dispatch:
plain Python values take the original Python control flow, traced Tensors
lower to ``lax.cond`` / ``lax.while_loop`` (via static.nn).  Conversion is
semantics-preserving eagerly, so a converted forward runs identically
eager and under ``@to_static`` — the dygraph↔static equivalence contract
(reference test suite: unittests/dygraph_to_static/, 72 files).
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap

import jax

from ..core.tensor import Tensor


# ---------------------------------------------------------------------------
# runtime converters (reference: dygraph_to_static/convert_operators.py)

def _is_traced_tensor(x):
    return isinstance(x, Tensor) and isinstance(x._data, jax.core.Tracer)


def _to_bool_pred(x):
    """Scalar-ify a tensor predicate (paddle requires numel()==1 here)."""
    import jax.numpy as jnp
    arr = x._data
    if arr.ndim:
        arr = jnp.reshape(arr, ())
    return arr.astype(bool)


def convert_ifelse(pred, true_fn, false_fn):
    """reference: convert_operators.convert_ifelse.

    Traced path: both branches are traced and merged leafwise with
    ``lax.select`` (the canonical XLA lowering of a scalar-predicated
    branch; avoids lax.cond's pytree-structure pitfalls while XLA still
    DCEs whichever side is dead under constant folding)."""
    if _is_traced_tensor(pred):
        import jax.numpy as jnp
        from ..ops import where as _ops_where, reshape as _ops_reshape
        from ..ops import cast as _ops_cast

        p_t = pred if pred.ndim == 0 else _ops_reshape(pred, [])
        if str(p_t.dtype) != "bool":
            p_t = _ops_cast(p_t, "bool")
        t_out = true_fn()
        f_out = false_fn()
        t_flat, t_isseq = _flatten_branch(t_out)
        f_flat, _ = _flatten_branch(f_out)
        if len(t_flat) != len(f_flat):
            raise UnsupportedControlFlow(
                "if/else branches produce different numbers of values")
        merged = []
        for tv, fv in zip(t_flat, f_flat):
            tu, fu = _unwrap(tv), _unwrap(fv)
            if isinstance(tu, _Undefined) or isinstance(fu, _Undefined):
                missing = tu if isinstance(tu, _Undefined) else fu
                if isinstance(tu, _Undefined) and isinstance(fu, _Undefined):
                    merged.append(tu)  # untouched on both sides
                    continue
                raise UnsupportedControlFlow(
                    f"variable {missing!r} is assigned in only one branch "
                    "of a tensor-predicated if/else — initialize it before "
                    "the if (reference: ifelse_transformer)")
            if hasattr(tu, "dtype") or hasattr(fu, "dtype") or \
                    isinstance(tu, (int, float, bool)):
                if jnp.asarray(tu).shape != jnp.asarray(fu).shape or \
                        jnp.asarray(tu).dtype != jnp.asarray(fu).dtype:
                    raise UnsupportedControlFlow(
                        "if/else branch outputs disagree in shape/dtype: "
                        f"{jnp.asarray(tu).shape}/{jnp.asarray(tu).dtype} "
                        f"vs {jnp.asarray(fu).shape}/{jnp.asarray(fu).dtype}")
                # merge through the DISPATCHED where op so the eager tape
                # (when grad is enabled during the trace) records the
                # select — raw jnp.where would sever backward at the if
                tt = tv if isinstance(tv, Tensor) else Tensor(tu)
                ft = fv if isinstance(fv, Tensor) else Tensor(fu)
                merged.append(_ops_where(p_t, tt, ft))
            else:
                if tu is not fu and tu != fu:
                    raise UnsupportedControlFlow(
                        "if/else branches bind a non-tensor value "
                        f"differently ({tu!r} vs {fu!r}) under a tensor "
                        "predicate")
                merged.append(tu)
        return tuple(merged) if t_isseq else merged[0]
    if isinstance(pred, Tensor):
        pred = bool(pred.numpy().reshape(()))
    return true_fn() if pred else false_fn()


def _flatten_branch(out):
    if isinstance(out, tuple):
        return list(out), True
    return [out], False


def _unwrap(out):
    if isinstance(out, Tensor):
        return out._data
    if isinstance(out, (tuple, list)):
        return type(out)(_unwrap(o) for o in out)
    return out


def _rewrap(out):
    if isinstance(out, (tuple, list)):
        return type(out)(_rewrap(o) for o in out)
    if hasattr(out, "dtype"):
        return Tensor(out, stop_gradient=True)
    return out


def convert_while_loop(cond_fn, body_fn, loop_vars, names=()):
    """reference: convert_operators.convert_while_loop.

    Traced tensor-predicated loops lower to ``lax.while_loop``, which is
    FORWARD-ONLY in reverse-mode autodiff (jax raises if a gradient path
    crosses it).  Trainable loops need a static trip count — write
    ``for i in range(n)`` (trace-unrolled) or use lax.scan via
    static.nn.while_loop's scan form — matching the reference's
    while_op, whose grad also requires recorded-iteration replay."""
    probe = cond_fn(*loop_vars)
    if _is_traced_tensor(probe) or any(
            _is_traced_tensor(v) for v in loop_vars):
        from jax import lax

        for i, v in enumerate(loop_vars):
            if isinstance(v, _Undefined):
                nm = v.name or (names[i] if i < len(names) else f"#{i}")
                raise UnsupportedControlFlow(
                    f"variable '{nm}' is created inside a tensor-"
                    "predicated while body — initialize it before the "
                    "loop so its shape/dtype is known "
                    "(reference: loop_transformer)")
        init = tuple(_unwrap(v) for v in loop_vars)

        def cond(state):
            return _to_bool_pred_arr(
                _unwrap(cond_fn(*[_rewrap_one(s) for s in state])))

        def body(state):
            out = body_fn(*[_rewrap_one(s) for s in state])
            if not isinstance(out, tuple):
                out = (out,)
            return tuple(_unwrap(o) for o in out)

        final = lax.while_loop(cond, body, init)
        return tuple(_rewrap_one(f) for f in final)
    # plain Python loop
    vals = tuple(loop_vars)
    while _plain_bool(cond_fn(*vals)):
        out = body_fn(*vals)
        vals = out if isinstance(out, tuple) else (out,)
    return vals


def _rewrap_one(x):
    return Tensor(x, stop_gradient=True) if hasattr(x, "dtype") else x


def _to_bool_pred_arr(arr):
    import jax.numpy as jnp
    if hasattr(arr, "ndim") and arr.ndim:
        arr = jnp.reshape(arr, ())
    return arr.astype(bool) if hasattr(arr, "astype") else bool(arr)


def _plain_bool(x):
    if isinstance(x, Tensor):
        return bool(x.numpy().reshape(()))
    return bool(x)


class _Undefined:
    """Sentinel for names not yet bound when a converted region starts
    (reference: dygraph_to_static UndefinedVar)."""

    __slots__ = ("name",)

    def __init__(self, name=""):
        self.name = name

    def __repr__(self):
        return f"<undefined '{self.name}'>"


UNDEFINED = _Undefined()


def lookup(name, local_map):
    """Preamble helper: current binding of ``name`` or an UNDEFINED
    sentinel (emitted by the transformer before converted regions)."""
    v = local_map.get(name, UNDEFINED)
    return _Undefined(name) if v is UNDEFINED else v


def range_cond(i, stop, step):
    """Loop-continue predicate for a converted for-range: direction-aware
    like Python's range (empty when step moves away from stop)."""
    if isinstance(i, Tensor) or isinstance(stop, Tensor) or \
            isinstance(step, Tensor):
        from ..ops import logical_and as _land, logical_or as _lor
        from ..core.dispatch import ensure_tensor
        i_t, stop_t = ensure_tensor(i), ensure_tensor(stop)
        step_t = ensure_tensor(step)
        fwd = _land(step_t > 0, i_t < stop_t)
        bwd = _land(step_t < 0, i_t > stop_t)
        return _lor(fwd, bwd)
    return (step > 0 and i < stop) or (step < 0 and i > stop)


def convert_logical_and(lhs_fn, rhs_fn):
    """reference: convert_operators.convert_logical_and (short-circuit
    preserved for plain Python values)."""
    lhs = lhs_fn()
    if isinstance(lhs, Tensor):
        rhs = rhs_fn()
        from ..ops import logical_and as _land
        return _land(_as_bool_tensor(lhs), _as_bool_tensor(rhs))
    return lhs and rhs_fn()


def convert_logical_or(lhs_fn, rhs_fn):
    lhs = lhs_fn()
    if _is_traced_tensor(lhs) or isinstance(lhs, Tensor):
        rhs = rhs_fn()
        from ..ops import logical_or as _lor
        return _lor(_as_bool_tensor(lhs), _as_bool_tensor(rhs))
    return lhs or rhs_fn()


def convert_call(fn):
    """reference: convert_operators.convert_call — recursively convert a
    callee.  Conversion here is per-decorated-function; callees trace."""
    return fn


def convert_logical_not(x):
    if isinstance(x, Tensor):
        from ..ops import logical_not as _lnot
        return _lnot(_as_bool_tensor(x))
    return not x


def _as_bool_tensor(x):
    if isinstance(x, Tensor):
        if str(x.dtype) != "bool":
            from ..ops import cast
            return cast(x, "bool")
        return x
    return x


# ---------------------------------------------------------------------------
# AST transformer

class UnsupportedControlFlow(Exception):
    pass


class _AssignedNames(ast.NodeVisitor):
    def __init__(self):
        self.names = []

    def visit_Assign(self, node):
        for t in node.targets:
            self._collect(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._collect(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._collect(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._collect(node.target)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        if not node.name.startswith("__d2s_"):
            self.names.append(node.name)  # the def itself binds a name

    def _collect(self, target):
        if isinstance(target, ast.Name):
            # generated helper names (__d2s_*) from already-transformed
            # nested regions are implementation detail, not user state —
            # threading them would poison the branch-merge/loop-vars
            if target.id.startswith("__d2s_"):
                return
            if target.id not in self.names:
                self.names.append(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._collect(e)
        # subscript/attribute targets mutate objects, not names


def _assigned_names(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


class _LoadedNames(ast.NodeVisitor):
    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.names.add(node.id)


def _loaded_names(nodes):
    v = _LoadedNames()
    for n in nodes:
        v.visit(n)
    return v.names


def _has(stmts, kinds):
    """True if any node of ``kinds`` appears in ``stmts`` WITHOUT
    crossing into a nested function scope (a return inside a nested def
    — e.g. an already-converted inner region's closure — exits that def,
    not the function being analyzed)."""

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, kinds):
                return True
            if walk(child):
                return True
        return False

    for s in stmts:
        if isinstance(s, kinds):
            return True
        if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and walk(s):
            return True
    return False


_JST = "_paddle_tpu_jst"


def _jst_call(fn_name, args):
    return ast.Call(
        func=ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                           attr=fn_name, ctx=ast.Load()),
        args=args, keywords=[])


def _preamble(names, n):
    """``x = _JST.lookup('x', dict(locals()))`` per name: binds names that
    may not exist yet to an UNDEFINED sentinel (reference: UndefinedVar),
    so converted closures can always read them."""
    map_name = f"__d2s_map_{n}"
    stmts = [ast.Assign(
        targets=[ast.Name(id=map_name, ctx=ast.Store())],
        value=ast.Call(func=ast.Name(id="dict", ctx=ast.Load()),
                       args=[ast.Call(func=ast.Name(id="locals",
                                                    ctx=ast.Load()),
                                      args=[], keywords=[])],
                       keywords=[]))]
    for name in names:
        stmts.append(ast.Assign(
            targets=[ast.Name(id=name, ctx=ast.Store())],
            value=_jst_call("lookup",
                            [ast.Constant(name),
                             ast.Name(id=map_name, ctx=ast.Load())])))
    return stmts


class _ControlFlowTransformer(ast.NodeTransformer):
    """Rewrites If / While / BoolOp / Not into converter calls.

    ``if``/``while`` whose condition could be tensor-valued become closure
    pairs + a converter call; names assigned inside become the
    returned/threaded variables (the reference's ifelse/loop transformers).
    """

    def __init__(self):
        self.counter = 0
        self._ret_flags = []

    # -- if/else ----------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        n = self.counter
        self.counter += 1
        body, orelse = node.body, node.orelse or [ast.Pass()]

        if _has(body + orelse, (ast.Break, ast.Continue)):
            # leave untouched: converter can't thread break/continue —
            # tracing will raise the helpful error if the pred is a tensor
            return node
        # returns are only convertible in the symmetric both-branches-end-
        # with-return form; ANY other return (nested in for/with/try, or
        # asymmetric) keeps Python semantics — a return inside a closure
        # would silently exit the closure instead of the function
        body_returns = isinstance(body[-1], ast.Return)
        else_returns = isinstance(orelse[-1], ast.Return)
        nested_returns = (_has(body[:-1] if body_returns else body,
                               ast.Return) or
                          _has(orelse[:-1] if else_returns else orelse,
                               ast.Return))
        if nested_returns or body_returns != else_returns:
            return node

        ret_name = f"__d2s_ret_{n}"
        if body_returns:
            body = [*body[:-1], ast.Assign(
                targets=[ast.Name(id=ret_name, ctx=ast.Store())],
                value=body[-1].value or ast.Constant(None))]
            orelse = [*orelse[:-1], ast.Assign(
                targets=[ast.Name(id=ret_name, ctx=ast.Store())],
                value=orelse[-1].value or ast.Constant(None))]

        assigned = _assigned_names(body + orelse)
        if body_returns:
            # the return-value carrier is generated (filtered by the
            # __d2s_ guard) but must thread through the branch closures
            assigned.append(ret_name)
        true_name, false_name = f"__d2s_true_{n}", f"__d2s_false_{n}"
        ret_tuple = ast.Tuple(
            elts=[ast.Name(id=a, ctx=ast.Load()) for a in assigned],
            ctx=ast.Load())

        def mkfn(name, stmts):
            # each assigned name becomes a defaulted parameter seeded from
            # the enclosing binding (the preamble guarantees it exists),
            # so a conditionally-bound name inside the closure can never
            # raise UnboundLocalError — it keeps its pre-if value, exactly
            # as the original straight-line code would
            return ast.FunctionDef(
                name=name,
                args=ast.arguments(
                    posonlyargs=[],
                    args=[ast.arg(arg=a) for a in assigned],
                    kwonlyargs=[], kw_defaults=[],
                    defaults=[ast.Name(id=a, ctx=ast.Load())
                              for a in assigned]),
                body=[*stmts, ast.Return(value=ret_tuple)],
                decorator_list=[])

        call = _jst_call("convert_ifelse",
                         [node.test,
                          ast.Name(id=true_name, ctx=ast.Load()),
                          ast.Name(id=false_name, ctx=ast.Load())])
        target = ast.Tuple(
            elts=[ast.Name(id=a, ctx=ast.Store()) for a in assigned],
            ctx=ast.Store())
        out = [*_preamble(assigned, n),
               mkfn(true_name, body), mkfn(false_name, orelse),
               ast.Assign(targets=[target], value=call)
               if assigned else ast.Expr(value=call)]
        if body_returns:
            out.append(ast.Return(value=ast.Name(id=ret_name,
                                                 ctx=ast.Load())))
        return [ast.fix_missing_locations(ast.copy_location(s, node))
                for s in out]

    # -- while ------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _has(node.body, (ast.Break, ast.Continue,
                                           ast.Return)):
            return node  # tracing will raise the guided error if needed
        n = self.counter
        self.counter += 1
        # loop state = names assigned in the body (they must pre-exist;
        # the preamble binds missing ones to the UNDEFINED sentinel and
        # the converter raises a named error on the traced path)
        loop_vars = _assigned_names(node.body)
        cond_name, body_name = f"__d2s_cond_{n}", f"__d2s_body_{n}"
        args = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=a) for a in loop_vars],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        ret_tuple = ast.Tuple(
            elts=[ast.Name(id=a, ctx=ast.Load()) for a in loop_vars],
            ctx=ast.Load())
        cond_fn = ast.FunctionDef(
            name=cond_name, args=args,
            body=[ast.Return(value=node.test)], decorator_list=[])
        body_fn = ast.FunctionDef(
            name=body_name, args=args,
            body=[*node.body, ast.Return(value=ret_tuple)],
            decorator_list=[])
        call = _jst_call(
            "convert_while_loop",
            [ast.Name(id=cond_name, ctx=ast.Load()),
             ast.Name(id=body_name, ctx=ast.Load()),
             ast.Tuple(elts=[ast.Name(id=a, ctx=ast.Load())
                             for a in loop_vars], ctx=ast.Load()),
             ast.Tuple(elts=[ast.Constant(a) for a in loop_vars],
                       ctx=ast.Load())])
        target = ast.Tuple(
            elts=[ast.Name(id=a, ctx=ast.Store()) for a in loop_vars],
            ctx=ast.Store())
        out = [*_preamble(loop_vars, n), cond_fn, body_fn,
               ast.Assign(targets=[target], value=call)
               if loop_vars else ast.Expr(value=call)]
        return [ast.fix_missing_locations(ast.copy_location(s, node))
                for s in out]

    # -- for over range() -------------------------------------------------
    def visit_For(self, node):
        """``for i in range(...)`` → while form (reference:
        loop_transformer converts for→while); a tensor bound then lowers
        through convert_while_loop.  Non-range iterables (lists,
        LayerList, tensors) keep Python semantics — iterating a module
        list is the common case and must trace-unroll.

        Known divergence (same as the reference's transformer): after
        the loop the induction variable holds the one-past value
        (start + step*n), not Python's last-yielded value."""
        self.generic_visit(node)
        it = node.iter
        if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and not it.keywords
                and 1 <= len(it.args) <= 3
                and isinstance(node.target, ast.Name)
                and not node.orelse
                and not _has(node.body, (ast.Break, ast.Continue,
                                         ast.Return))):
            return node
        n = self.counter
        self.counter += 1
        i_name = node.target.id
        if len(it.args) == 1:
            start, stop, step = (ast.Constant(0), it.args[0],
                                 ast.Constant(1))
        elif len(it.args) == 2:
            start, stop, step = (it.args[0], it.args[1], ast.Constant(1))
        else:
            start, stop, step = it.args[0], it.args[1], it.args[2]
        stop_name, step_name = f"__d2s_stop_{n}", f"__d2s_step_{n}"
        init = [
            ast.Assign(targets=[ast.Name(id=stop_name, ctx=ast.Store())],
                       value=stop),
            ast.Assign(targets=[ast.Name(id=step_name, ctx=ast.Store())],
                       value=step),
            ast.Assign(targets=[ast.Name(id=i_name, ctx=ast.Store())],
                       value=start),
        ]
        loop_vars = [i_name] + [a for a in _assigned_names(node.body)
                                if a != i_name]
        cond_name, body_name = f"__d2s_fcond_{n}", f"__d2s_fbody_{n}"
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=a) for a in loop_vars],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        cond_fn = ast.FunctionDef(
            name=cond_name, args=args,
            body=[ast.Return(value=_jst_call(
                "range_cond",
                [ast.Name(id=i_name, ctx=ast.Load()),
                 ast.Name(id=stop_name, ctx=ast.Load()),
                 ast.Name(id=step_name, ctx=ast.Load())]))],
            decorator_list=[])
        incr = ast.Assign(
            targets=[ast.Name(id=i_name, ctx=ast.Store())],
            value=ast.BinOp(left=ast.Name(id=i_name, ctx=ast.Load()),
                            op=ast.Add(),
                            right=ast.Name(id=step_name, ctx=ast.Load())))
        ret_tuple = ast.Tuple(
            elts=[ast.Name(id=a, ctx=ast.Load()) for a in loop_vars],
            ctx=ast.Load())
        body_fn = ast.FunctionDef(
            name=body_name, args=args,
            body=[*node.body, incr, ast.Return(value=ret_tuple)],
            decorator_list=[])
        call = _jst_call(
            "convert_while_loop",
            [ast.Name(id=cond_name, ctx=ast.Load()),
             ast.Name(id=body_name, ctx=ast.Load()),
             ast.Tuple(elts=[ast.Name(id=a, ctx=ast.Load())
                             for a in loop_vars], ctx=ast.Load()),
             ast.Tuple(elts=[ast.Constant(a) for a in loop_vars],
                       ctx=ast.Load())])
        target = ast.Tuple(
            elts=[ast.Name(id=a, ctx=ast.Store()) for a in loop_vars],
            ctx=ast.Store())
        out = [*_preamble([a for a in loop_vars if a != i_name], n),
               *init, cond_fn, body_fn,
               ast.Assign(targets=[target], value=call)]
        return [ast.fix_missing_locations(ast.copy_location(s, node))
                for s in out]

    # -- bool ops ---------------------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        expr = node.values[-1]
        for v in reversed(node.values[:-1]):
            thunk = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=expr)
            lhs_thunk = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=v)
            expr = _jst_call(fn, [lhs_thunk, thunk])
        return ast.copy_location(expr, node)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.copy_location(
                _jst_call("convert_logical_not", [node.operand]), node)
        return node


def convert_function(fn):
    """AST-convert ``fn``; returns the converted function or None when the
    source is unavailable/unconvertible (caller falls back to tracing)."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return None
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return None
    func_def = tree.body[0]
    if not isinstance(func_def, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    # only the to_static-family decorators may be stripped; any OTHER
    # decorator (autocast wrappers, caches...) would silently disappear
    # from the recompiled function — bail to the trace path instead
    for dec in func_def.decorator_list:
        name = dec
        while isinstance(name, (ast.Call, ast.Attribute)):
            name = name.func if isinstance(name, ast.Call) else name.attr
        dec_name = name if isinstance(name, str) else getattr(
            name, "id", "")
        if dec_name not in ("to_static", "declarative", "not_to_static"):
            return None
    func_def.decorator_list = []  # run once, undecorated
    if fn.__code__.co_freevars:
        # closures (including the implicit __class__ cell behind
        # zero-arg super()) cannot be faithfully rebuilt by exec — cells
        # would freeze to decoration-time snapshots and super() would
        # lose its cell entirely.  Fall back to the trace path.
        return None
    transformer = _ControlFlowTransformer()
    new_tree = transformer.visit(tree)
    if transformer.counter == 0:
        return None  # nothing to convert — tracing alone is enough
    ast.fix_missing_locations(new_tree)
    code = compile(new_tree, f"<dy2static:{fn.__qualname__}>", "exec")
    gl = dict(fn.__globals__)
    from . import dy2static as _self
    gl[_JST] = _self
    loc = {}
    exec(code, gl, loc)
    converted = loc[func_def.name]
    converted = functools.wraps(fn)(converted)
    converted.__wrapped_by_dy2static__ = True
    if fn.__defaults__:
        converted.__defaults__ = fn.__defaults__
    return converted
