"""dygraph→static AST conversion: tensor-dependent Python control flow.

Reference parity: ``fluid/dygraph/dygraph_to_static/`` — the AST
transformer pipeline (``program_translator.py:756``; ifelse_transformer,
loop_transformer, logical_transformer).  The reference rewrites ``if``/
``while``/``and``/``or``/``not`` into ``convert_ifelse``/
``convert_while_loop``/``convert_logical_*`` calls that dispatch on
whether the condition is a Variable.

TPU-native design: same two-stage shape — an ``ast.NodeTransformer``
rewrites the decorated function once, and the runtime converters dispatch:
plain Python values take the original Python control flow, traced Tensors
lower to ``lax.cond`` / ``lax.while_loop`` (via static.nn).  Conversion is
semantics-preserving eagerly, so a converted forward runs identically
eager and under ``@to_static`` — the dygraph↔static equivalence contract
(reference test suite: unittests/dygraph_to_static/, 72 files).
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap

import jax

from ..core.tensor import Tensor


# ---------------------------------------------------------------------------
# runtime converters (reference: dygraph_to_static/convert_operators.py)

def _is_traced_tensor(x):
    return isinstance(x, Tensor) and isinstance(x._data, jax.core.Tracer)


def _to_bool_pred(x):
    """Scalar-ify a tensor predicate (paddle requires numel()==1 here)."""
    import jax.numpy as jnp
    arr = x._data
    if arr.ndim:
        arr = jnp.reshape(arr, ())
    return arr.astype(bool)


def convert_ifelse(pred, true_fn, false_fn):
    """reference: convert_operators.convert_ifelse.

    Traced path: both branches are traced and merged leafwise with
    ``lax.select`` (the canonical XLA lowering of a scalar-predicated
    branch; avoids lax.cond's pytree-structure pitfalls while XLA still
    DCEs whichever side is dead under constant folding).

    .. warning:: Under a TRACED tensor predicate BOTH branches always
       execute — unlike the reference's real-branch dispatch.  A branch
       guarding numerically unsafe math (``if s > 0: y = 1/s``) still
       evaluates the unsafe side, and the where-gradient trap propagates
       NaN/Inf *gradients* from the unselected branch even though the
       forward value is correct.  Guard unsafe math inside the branch
       itself (``1/jnp.where(s > 0, s, 1)``-style "double-where"), or
       keep the predicate a Python value so the branch dispatches for
       real.  Eager (concrete) tensor predicates are unaffected — they
       pick one branch.  Converted side effects (``print``/``assert``)
       ARE gated correctly: they consult the branch-activity mask and
       stay silent in the unselected branch."""
    if _is_traced_tensor(pred):
        import jax.numpy as jnp
        from ..ops import where as _ops_where, reshape as _ops_reshape
        from ..ops import cast as _ops_cast

        p_t = pred if pred.ndim == 0 else _ops_reshape(pred, [])
        if str(p_t.dtype) != "bool":
            p_t = _ops_cast(p_t, "bool")
        # record which branch is semantically active while tracing each
        # closure: side-effect converters (assert/print) consult this so
        # the UNSELECTED branch's effects stay silent even though both
        # branches execute under the where-merge
        _active_branch_preds.append(p_t._data)
        try:
            t_out = true_fn()
        finally:
            _active_branch_preds.pop()
        _active_branch_preds.append(jnp.logical_not(p_t._data))
        try:
            f_out = false_fn()
        finally:
            _active_branch_preds.pop()
        t_flat, t_isseq = _flatten_branch(t_out)
        f_flat, _ = _flatten_branch(f_out)
        if len(t_flat) != len(f_flat):
            raise UnsupportedControlFlow(
                "if/else branches produce different numbers of values")
        merged = []
        for tv, fv in zip(t_flat, f_flat):
            tu, fu = _unwrap(tv), _unwrap(fv)
            if isinstance(tu, _Undefined) or isinstance(fu, _Undefined):
                missing = tu if isinstance(tu, _Undefined) else fu
                if isinstance(tu, _Undefined) and isinstance(fu, _Undefined):
                    merged.append(tu)  # untouched on both sides
                    continue
                raise UnsupportedControlFlow(
                    f"variable {missing!r} is assigned in only one branch "
                    "of a tensor-predicated if/else — initialize it before "
                    "the if (reference: ifelse_transformer)")
            if (tu is None) != (fu is None):
                raise UnsupportedControlFlow(
                    "a tensor-predicated if/else merges a tensor with "
                    "None — e.g. a return value that exists on only one "
                    "path, or a variable pre-initialized to None.  "
                    "Initialize it to a tensor of the final shape/dtype "
                    "before the branch (for return-in-loop: assign a "
                    "result variable and break instead; reference: "
                    "return_transformer.py)")
            if hasattr(tu, "dtype") or hasattr(fu, "dtype") or \
                    isinstance(tu, (int, float, bool)):
                if jnp.asarray(tu).shape != jnp.asarray(fu).shape or \
                        jnp.asarray(tu).dtype != jnp.asarray(fu).dtype:
                    raise UnsupportedControlFlow(
                        "if/else branch outputs disagree in shape/dtype: "
                        f"{jnp.asarray(tu).shape}/{jnp.asarray(tu).dtype} "
                        f"vs {jnp.asarray(fu).shape}/{jnp.asarray(fu).dtype}")
                # merge through the DISPATCHED where op so the eager tape
                # (when grad is enabled during the trace) records the
                # select — raw jnp.where would sever backward at the if
                tt = tv if isinstance(tv, Tensor) else Tensor(tu)
                ft = fv if isinstance(fv, Tensor) else Tensor(fu)
                merged.append(_ops_where(p_t, tt, ft))
            else:
                if tu is not fu and tu != fu:
                    raise UnsupportedControlFlow(
                        "if/else branches bind a non-tensor value "
                        f"differently ({tu!r} vs {fu!r}) under a tensor "
                        "predicate")
                merged.append(tu)
        return tuple(merged) if t_isseq else merged[0]
    if isinstance(pred, Tensor):
        pred = bool(pred.numpy().reshape(()))
    return true_fn() if pred else false_fn()


def _flatten_branch(out):
    if isinstance(out, tuple):
        return list(out), True
    return [out], False


def _unwrap(out):
    if isinstance(out, Tensor):
        return out._data
    if isinstance(out, (tuple, list)):
        return type(out)(_unwrap(o) for o in out)
    return out


def _rewrap(out):
    if isinstance(out, (tuple, list)):
        return type(out)(_rewrap(o) for o in out)
    if hasattr(out, "dtype"):
        return Tensor(out, stop_gradient=True)
    return out


def convert_while_loop(cond_fn, body_fn, loop_vars, names=()):
    """reference: convert_operators.convert_while_loop.

    Traced tensor-predicated loops lower to ``lax.while_loop``, which is
    FORWARD-ONLY in reverse-mode autodiff (jax raises if a gradient path
    crosses it).  Trainable loops need a static trip count — write
    ``for i in range(n)`` (trace-unrolled) or use lax.scan via
    static.nn.while_loop's scan form — matching the reference's
    while_op, whose grad also requires recorded-iteration replay."""
    probe = cond_fn(*loop_vars)
    if _is_traced_tensor(probe) or any(
            _is_traced_tensor(v) for v in loop_vars):
        from jax import lax

        for i, v in enumerate(loop_vars):
            if isinstance(v, _Undefined):
                nm = v.name or (names[i] if i < len(names) else f"#{i}")
                raise UnsupportedControlFlow(
                    f"variable '{nm}' is created inside a tensor-"
                    "predicated while body — initialize it before the "
                    "loop so its shape/dtype is known "
                    "(reference: loop_transformer)")
        init = tuple(_unwrap(v) for v in loop_vars)

        def cond(state):
            return _to_bool_pred_arr(
                _unwrap(cond_fn(*[_rewrap_one(s) for s in state])))

        def body(state):
            out = body_fn(*[_rewrap_one(s) for s in state])
            if not isinstance(out, tuple):
                out = (out,)
            return tuple(_unwrap(o) for o in out)

        try:
            final = lax.while_loop(cond, body, init)
        except TypeError as e:
            msg = str(e)
            if not any(k in msg for k in ("carry", "body_fun", "body "
                                          "function", "while_loop")):
                raise  # a genuine user TypeError from tracing the body
            raise UnsupportedControlFlow(
                "tensor-predicated loop carry changed structure/dtype "
                "between iterations (e.g. a variable first bound inside "
                "the loop, or a return-in-loop whose value has no "
                "pre-loop binding).  Initialize every loop-carried "
                f"variable before the loop.  [{e}]") from e
        return tuple(_rewrap_one(f) for f in final)
    # plain Python loop.  The condition may BECOME traced mid-loop even
    # though every initial loop var was concrete — e.g. an exit-flag
    # rewrite whose break predicate reads a traced activation sets the
    # flag to a where-merged tracer on iteration 1.  Iterations already
    # executed are simply unrolled into the trace; the remainder
    # re-dispatches onto the lax.while_loop path with the current values
    # as the carry.
    vals = tuple(loop_vars)
    while True:
        c = cond_fn(*vals)
        if _is_traced_tensor(c):
            # (a traced accumulator with a still-Python condition keeps
            # unrolling — that path stays differentiable)
            return convert_while_loop(cond_fn, body_fn, vals, names)
        if not _plain_bool(c):
            break
        out = body_fn(*vals)
        vals = out if isinstance(out, tuple) else (out,)
    return vals


def _rewrap_one(x):
    return Tensor(x, stop_gradient=True) if hasattr(x, "dtype") else x


def _to_bool_pred_arr(arr):
    import jax.numpy as jnp
    if hasattr(arr, "ndim") and arr.ndim:
        arr = jnp.reshape(arr, ())
    return arr.astype(bool) if hasattr(arr, "astype") else bool(arr)


def _plain_bool(x):
    if isinstance(x, Tensor):
        return bool(x.numpy().reshape(()))
    return bool(x)


class _Undefined:
    """Sentinel for names not yet bound when a converted region starts
    (reference: dygraph_to_static UndefinedVar)."""

    __slots__ = ("name",)

    def __init__(self, name=""):
        self.name = name

    def __repr__(self):
        return f"<undefined '{self.name}'>"


UNDEFINED = _Undefined()


def lookup(name, local_map):
    """Preamble helper: current binding of ``name`` or an UNDEFINED
    sentinel (emitted by the transformer before converted regions)."""
    v = local_map.get(name, UNDEFINED)
    return _Undefined(name) if v is UNDEFINED else v


def range_cond(i, stop, step):
    """Loop-continue predicate for a converted for-range: direction-aware
    like Python's range (empty when step moves away from stop)."""
    if isinstance(i, Tensor) or isinstance(stop, Tensor) or \
            isinstance(step, Tensor):
        from ..ops import logical_and as _land, logical_or as _lor
        from ..core.dispatch import ensure_tensor
        i_t, stop_t = ensure_tensor(i), ensure_tensor(stop)
        step_t = ensure_tensor(step)
        fwd = _land(step_t > 0, i_t < stop_t)
        bwd = _land(step_t < 0, i_t > stop_t)
        return _lor(fwd, bwd)
    return (step > 0 and i < stop) or (step < 0 and i > stop)


def convert_logical_and(lhs_fn, rhs_fn):
    """reference: convert_operators.convert_logical_and (short-circuit
    preserved for plain Python values)."""
    lhs = lhs_fn()
    if isinstance(lhs, Tensor):
        rhs = rhs_fn()
        from ..ops import logical_and as _land
        return _land(_as_bool_tensor(lhs), _as_bool_tensor(rhs))
    return lhs and rhs_fn()


def convert_logical_or(lhs_fn, rhs_fn):
    lhs = lhs_fn()
    if _is_traced_tensor(lhs) or isinstance(lhs, Tensor):
        rhs = rhs_fn()
        from ..ops import logical_or as _lor
        return _lor(_as_bool_tensor(lhs), _as_bool_tensor(rhs))
    return lhs or rhs_fn()


def convert_call(fn):
    """reference: convert_operators.convert_call — recursively convert a
    callee.  Conversion here is per-decorated-function; callees trace."""
    return fn


# traced bool preds of the enclosing tensor-predicated if branches —
# pushed/popped by convert_ifelse around each branch closure so that
# side-effect converters (assert/print) can stay silent in the branch
# the predicate did not select (both branches EXECUTE under the
# where-merge; see convert_ifelse's warning)
_active_branch_preds = []


def _branch_active_mask():
    """AND of the enclosing tensor-if branch predicates, or None when
    not inside any tensor-predicated branch."""
    if not _active_branch_preds:
        return None
    import jax.numpy as jnp
    m = _active_branch_preds[0]
    for p in _active_branch_preds[1:]:
        m = jnp.logical_and(m, p)
    return m


def convert_assert(test, msg_fn=None):
    """reference: dygraph_to_static/assert_transformer.py — ``assert`` on
    a traced tensor becomes the Assert op (runtime check + abort).  Here
    an ordered host callback raises AssertionError when the predicate
    fails at run time; an untransformed assert would truthy-test a
    TRACER and raise a confusing TracerBoolConversionError at trace
    time.  Host-side predicates keep plain-assert semantics, including
    not evaluating the (lazy) message unless the assert fails.  Inside
    a tensor-predicated if, the check is gated on the branch actually
    being selected."""
    pred = test._data if isinstance(test, Tensor) else test
    if not isinstance(pred, jax.core.Tracer):
        active = _branch_active_mask()
        if active is None:
            if not test:
                raise AssertionError(
                    msg_fn() if msg_fn is not None else None)
            return
        # concrete predicate inside a TRACED branch: still gate on the
        # branch mask at run time
        import jax.numpy as jnp
        pred = jnp.asarray(bool(test))

    import jax.numpy as jnp
    import numpy as _np
    ok = jnp.all(pred)
    active = _branch_active_mask()
    violated = jnp.logical_not(ok) if active is None else \
        jnp.logical_and(active, jnp.logical_not(ok))
    # the message may reference traced values — it can only be built at
    # trace time (tracer reprs render as <traced>)
    msg = msg_fn() if msg_fn is not None else None

    def host_check(bad):
        # plain numpy only: calling back into jax from inside a debug
        # callback is documented deadlock-bait
        if bool(_np.asarray(bad)):
            raise AssertionError(
                msg if msg is not None else "dy2static assert failed")

    jax.debug.callback(host_check, violated, ordered=True)


def convert_print(*args, sep=" ", end="\n", **kwargs):
    """reference: dygraph_to_static/print_transformer.py — ``print`` on a
    traced tensor becomes the Print op; here ``jax.debug.print`` via a
    host callback that replays full builtin-print semantics (sep/end/
    file/flush), so the compiled program prints concrete values at run
    time (an untransformed print would fire once at TRACE time with
    abstract values).  Host-side values keep builtin print directly."""
    is_arr = [_is_traced_tensor(a) or isinstance(a, jax.core.Tracer)
              for a in args]
    active = _branch_active_mask()
    if not any(is_arr) and active is None:
        print(*args, sep=sep, end=end, **kwargs)
        return
    # the callback only transports arrays; static values (labels,
    # numbers) are closed over and re-inserted by position
    import jax.numpy as jnp
    import numpy as _np
    arrays = [a._data if isinstance(a, Tensor) else a
              for a, t in zip(args, is_arr) if t]
    statics = [a for a, t in zip(args, is_arr) if not t]
    if active is None:
        active = jnp.asarray(True)

    def host_print(act, *concrete):
        # skipped when the enclosing tensor-if branch was not selected
        # (both branches execute under the where-merge)
        if not bool(_np.asarray(act)):
            return
        # real builtin print: honors sep/end/file/flush and never
        # formats through jax.debug.print's str.format (whose parser
        # would choke on literal braces in the printed values)
        it_c, it_s = iter(concrete), iter(statics)
        merged = [next(it_c) if t else next(it_s) for t in is_arr]
        print(*merged, sep=sep, end=end, **kwargs)

    # ordered: consecutive prints must emit in program order (builtin
    # print and the reference Print op are strictly ordered)
    jax.debug.callback(host_print, active, *arrays, ordered=True)


def convert_logical_not(x):
    if isinstance(x, Tensor):
        from ..ops import logical_not as _lnot
        return _lnot(_as_bool_tensor(x))
    return not x


def _as_bool_tensor(x):
    if isinstance(x, Tensor):
        if str(x.dtype) != "bool":
            from ..ops import cast
            return cast(x, "bool")
        return x
    return x


def merge_return(ret_flag, ret_val, rest_fn):
    """Post-loop merge for return-in-loop (reference:
    dygraph_to_static/return_transformer.py RETURN_VALUE flag): if the
    early-exit flag is set, the loop returned; otherwise run the rest of
    the function.  A TRACED flag cannot pick a Python path — raise the
    guided error (restructure with break + a pre-initialized result
    variable, which threads through lax.while_loop)."""
    if _is_traced_tensor(ret_flag):
        raise UnsupportedControlFlow(
            "return inside a loop with a tensor-dependent exit cannot be "
            "lowered: the return value has no pre-loop binding for the "
            "lax.while_loop carry.  Initialize a result variable before "
            "the loop, assign it and `break` instead of returning "
            "(reference: return_transformer.py)")
    if _plain_bool(ret_flag):
        return ret_val
    return rest_fn()


# ---------------------------------------------------------------------------
# AST transformer

class UnsupportedControlFlow(Exception):
    pass


class _AssignedNames(ast.NodeVisitor):
    def __init__(self):
        self.names = []

    def visit_Assign(self, node):
        for t in node.targets:
            self._collect(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._collect(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._collect(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._collect(node.target)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        if not node.name.startswith("__d2s_"):
            self.names.append(node.name)  # the def itself binds a name

    def _collect(self, target):
        if isinstance(target, ast.Name):
            # generated helper names (__d2s_*) from already-transformed
            # nested regions are implementation detail, not user state —
            # threading them would poison the branch-merge/loop-vars
            if target.id.startswith("__d2s_"):
                return
            if target.id not in self.names:
                self.names.append(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._collect(e)
        # subscript/attribute targets mutate objects, not names


def _assigned_names(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


class _LoadedNames(ast.NodeVisitor):
    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.names.add(node.id)


def _loaded_names(nodes):
    v = _LoadedNames()
    for n in nodes:
        v.visit(n)
    return v.names


def _has(stmts, kinds):
    """True if any node of ``kinds`` appears in ``stmts`` WITHOUT
    crossing into a nested function scope (a return inside a nested def
    — e.g. an already-converted inner region's closure — exits that def,
    not the function being analyzed)."""

    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, kinds):
                return True
            if walk(child):
                return True
        return False

    for s in stmts:
        if isinstance(s, kinds):
            return True
        if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and walk(s):
            return True
    return False


_JST = "_paddle_tpu_jst"


def _jst_call(fn_name, args):
    return ast.Call(
        func=ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                           attr=fn_name, ctx=ast.Load()),
        args=args, keywords=[])


def _preamble(names, n):
    """``x = _JST.lookup('x', dict(locals()))`` per name: binds names that
    may not exist yet to an UNDEFINED sentinel (reference: UndefinedVar),
    so converted closures can always read them."""
    map_name = f"__d2s_map_{n}"
    stmts = [ast.Assign(
        targets=[ast.Name(id=map_name, ctx=ast.Store())],
        value=ast.Call(func=ast.Name(id="dict", ctx=ast.Load()),
                       args=[ast.Call(func=ast.Name(id="locals",
                                                    ctx=ast.Load()),
                                      args=[], keywords=[])],
                       keywords=[]))]
    for name in names:
        stmts.append(ast.Assign(
            targets=[ast.Name(id=name, ctx=ast.Store())],
            value=_jst_call("lookup",
                            [ast.Constant(name),
                             ast.Name(id=map_name, ctx=ast.Load())])))
    return stmts


class _Exits:
    __slots__ = ("brk", "cont", "ret_own", "ret_nested")

    def __init__(self):
        self.brk = self.cont = self.ret_own = self.ret_nested = False


def _scan_exits(stmts):
    """Exit statements of a loop body: break/continue bound to THIS loop
    vs return in this loop's own scope vs return hiding inside a nested
    loop.  Nested function scopes never count; nested loops capture
    break/continue but not return."""
    ex = _Exits()

    def walk(node, in_nested_loop):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Break):
                ex.brk = ex.brk or not in_nested_loop
            elif isinstance(child, ast.Continue):
                ex.cont = ex.cont or not in_nested_loop
            elif isinstance(child, ast.Return):
                if in_nested_loop:
                    ex.ret_nested = True
                else:
                    ex.ret_own = True
            walk(child, in_nested_loop
                 or isinstance(child, (ast.While, ast.For)))

    root = ast.Module(body=list(stmts), type_ignores=[])
    walk(root, False)
    return ex


def _name(n, ctx=None):
    return ast.Name(id=n, ctx=ctx or ast.Load())


def _assign(n, value):
    return ast.Assign(targets=[_name(n, ast.Store())], value=value)


class _LoopBailout(Exception):
    """Internal: this loop cannot be flag-rewritten; leave it as-is."""


def _is_range_for(node):
    """``for <Name> in range(a[, b[, c]]):`` with no else clause."""
    it = node.iter
    return (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
            and it.func.id == "range" and not it.keywords
            and 1 <= len(it.args) <= 3
            and isinstance(node.target, ast.Name) and not node.orelse)


def _for_range_to_while(node, tag):
    """Desugar ``for i in range(...)`` into (init stmts, While test,
    i-binding stmt, increment stmt) — the single range-for lowering shared
    by the exit pre-pass and the main transformer (reference:
    loop_transformer converts for→while).  ``tag`` namespaces the
    generated bindings.

    A hidden iterator variable carries the position; ``i = _it`` at the
    top of each iteration keeps the user's induction variable at the
    LAST-YIELDED value after the loop (matching Python — including after
    ``break``, where the unconditional increment only advances the hidden
    variable).  Sole divergence: an empty range leaves ``i`` at start
    instead of unbound."""
    it = node.iter
    i_name = node.target.id
    if len(it.args) == 1:
        start, stop, step = ast.Constant(0), it.args[0], ast.Constant(1)
    elif len(it.args) == 2:
        start, stop, step = it.args[0], it.args[1], ast.Constant(1)
    else:
        start, stop, step = it.args
    stop_name, step_name = f"_d2s_stop{tag}", f"_d2s_step{tag}"
    it_name = f"_d2s_it{tag}"
    init = [_assign(stop_name, stop), _assign(step_name, step),
            _assign(it_name, start), _assign(i_name, _name(it_name))]
    test = _jst_call("range_cond", [_name(it_name), _name(stop_name),
                                    _name(step_name)])
    bind_i = _assign(i_name, _name(it_name))
    incr = _assign(it_name, ast.BinOp(left=_name(it_name), op=ast.Add(),
                                      right=_name(step_name)))
    return init, test, bind_i, incr


class _LoopExitTransformer(ast.NodeTransformer):
    """Rewrites break / continue / return-in-loop into flag variables so
    the main transformer sees exit-free loops (reference:
    dygraph_to_static/break_continue_transformer.py and
    return_transformer.py run before loop_transformer for the same
    reason).

    * ``break``    -> ``brk = True``; the loop condition gains a
                      ``not brk and`` conjunct.
    * ``continue`` -> ``cont = True``; ``cont`` resets each iteration and
      statements after any flag-setting statement are wrapped in
      ``if not (brk or cont):`` — the guard bubbles through enclosing
      if/with blocks exactly like the reference's bubbling guards.
    * ``return e`` -> ``ret, rv, brk = True, e, True``; handled only for
      loops that are direct statements of the function body, where the
      trailing code becomes a ``__d2s_rest`` closure merged via
      ``_JST.merge_return`` after the loop.

    The rewrite is semantics-preserving for plain Python execution, so
    eager and converted runs stay identical; tensor-predicated flags then
    lower through the ordinary if/while converters.
    """

    def __init__(self):
        self.counter = 0
        self.changed = False

    # -- helpers ----------------------------------------------------------
    def _flags(self):
        n = self.counter
        self.counter += 1
        return (f"_d2s_brk{n}", f"_d2s_cont{n}", f"_d2s_ret{n}",
                f"_d2s_rv{n}", n)

    def _guard_test(self, flags_set):
        """``not (f1 or f2)`` over the flags that may be set."""
        flags = sorted(flags_set)
        expr = _name(flags[0])
        for f in flags[1:]:
            expr = ast.BoolOp(op=ast.Or(), values=[expr, _name(f)])
        return ast.UnaryOp(op=ast.Not(), operand=expr)

    def _rewrite_block(self, stmts, brk, cont, ret, rv):
        """Returns (new_stmts, set_flags) — set_flags nonempty when any
        path through these statements may set an exit flag, in which case
        the caller's trailing statements were already folded under a
        guard here."""
        out = []
        for idx, s in enumerate(stmts):
            new_s, set_flags = self._rewrite_stmt(s, brk, cont, ret, rv)
            out.extend(new_s)
            if set_flags:
                rest = stmts[idx + 1:]
                if rest:
                    rest_new, rest_flags = self._rewrite_block(
                        rest, brk, cont, ret, rv)
                    out.append(ast.If(test=self._guard_test(set_flags),
                                      body=rest_new, orelse=[]))
                    set_flags = set_flags | rest_flags
                return out, set_flags
        return out, set()

    def _rewrite_stmt(self, s, brk, cont, ret, rv):
        if isinstance(s, ast.Break):
            if brk is None:
                raise _LoopBailout  # can't happen: scan found breaks
            return [_assign(brk, ast.Constant(True))], {brk}
        if isinstance(s, ast.Continue):
            return [_assign(cont, ast.Constant(True))], {cont}
        if isinstance(s, ast.Return):
            if ret is None:
                # return in a loop we chose not to convert for returns
                raise _LoopBailout
            return [_assign(ret, ast.Constant(True)),
                    _assign(rv, s.value or ast.Constant(None)),
                    _assign(brk, ast.Constant(True))], {brk}
        if isinstance(s, ast.If):
            body, bf = self._rewrite_block(s.body, brk, cont, ret, rv)
            orelse, of = (self._rewrite_block(s.orelse, brk, cont, ret, rv)
                          if s.orelse else ([], set()))
            if not (bf or of):
                return [s], set()
            return [ast.If(test=s.test, body=body, orelse=orelse)], bf | of
        if isinstance(s, ast.With):
            body, bf = self._rewrite_block(s.body, brk, cont, ret, rv)
            if not bf:
                return [s], set()
            return [ast.With(items=s.items, body=body)], bf
        if isinstance(s, (ast.Try, getattr(ast, "TryStar", ast.Try))):
            # exits inside try/finally interact with handler semantics;
            # leave such loops to the trace path
            if _has([s], (ast.Break, ast.Continue, ast.Return)):
                raise _LoopBailout
            return [s], set()
        # nested loops own their break/continue (already rewritten
        # bottom-up); returns inside them were bailed on by the caller
        return [s], set()

    def _convert_loop(self, node, with_return):
        """Common flag rewrite for While and desugared For-range."""
        brk, cont, ret, rv, n = self._flags()
        ex = _scan_exits(node.body)
        has_brk = ex.brk or (with_return and ex.ret_own)
        body, _ = self._rewrite_block(
            node.body, brk if has_brk else None, cont,
            ret if with_return else None, rv)
        new_body = ([_assign(cont, ast.Constant(False))] if ex.cont
                    else []) + body
        test = node.test
        if has_brk:
            test = ast.BoolOp(
                op=ast.And(),
                values=[ast.UnaryOp(op=ast.Not(), operand=_name(brk)),
                        test])
        pre = []
        if has_brk:
            pre.append(_assign(brk, ast.Constant(False)))
        if ex.cont:
            # the reset inside the body makes cont loop-carried state; it
            # needs a pre-loop binding for the traced while carry
            pre.append(_assign(cont, ast.Constant(False)))
        if with_return:
            pre.extend([_assign(ret, ast.Constant(False)),
                        _assign(rv, ast.Constant(None))])
        new_loop = ast.While(test=test, body=new_body, orelse=[])
        self.changed = True
        return pre, new_loop, (ret, rv, n)

    # -- loop visitors (break/continue only; returns handled at the
    #    function level where the trailing code is visible) --------------
    def _maybe_convert(self, node, with_return=False):
        ex = _scan_exits(node.body)
        if ex.ret_nested or (ex.ret_own and not with_return):
            return None  # leave untouched -> trace fallback
        if not (ex.brk or ex.cont or (with_return and ex.ret_own)):
            return None
        if isinstance(node, ast.While):
            if node.orelse:
                return None
            try:
                return self._convert_loop(node, with_return and ex.ret_own)
            except _LoopBailout:
                return None
        if isinstance(node, ast.For):
            if not _is_range_for(node):
                return None  # python-iterable loops keep native exits
            init, test, bind_i, incr = _for_range_to_while(
                node, self.counter)
            # bind_i runs before the (guard-rewritten) user body; the
            # hidden-iterator increment runs unconditionally after it —
            # break leaves the user's induction variable at its
            # break-iteration value while only _it advances
            as_while = ast.While(test=test, body=[bind_i, *node.body],
                                 orelse=[])
            try:
                pre, loop, retinfo = self._convert_loop(
                    as_while, with_return and ex.ret_own)
            except _LoopBailout:
                return None
            loop.body.append(incr)
            return init + pre, loop, retinfo
        return None

    def visit_While(self, node):
        self.generic_visit(node)
        res = self._maybe_convert(node)
        if res is None:
            return node
        pre, loop, _ = res
        return [ast.fix_missing_locations(ast.copy_location(s, node))
                for s in (*pre, loop)]

    def visit_For(self, node):
        self.generic_visit(node)
        res = self._maybe_convert(node)
        if res is None:
            return node
        pre, loop, _ = res
        return [ast.fix_missing_locations(ast.copy_location(s, node))
                for s in (*pre, loop)]

    def visit_FunctionDef(self, node):
        """Top-level loops may additionally convert `return`: the code
        after the loop becomes a closure merged through merge_return."""
        self.generic_visit(node)  # converts break/continue everywhere
        body = node.body
        for idx, s in enumerate(body):
            if not isinstance(s, (ast.While, ast.For)):
                continue
            ex = _scan_exits(s.body)
            if not ex.ret_own or ex.ret_nested:
                continue
            res = self._maybe_convert(s, with_return=True)
            if res is None:
                continue
            pre, loop, retinfo = res
            ret, rv, n = retinfo
            rest_stmts = body[idx + 1:] or [ast.Pass()]
            rest_name = f"__d2s_rest_{n}"
            rest_fn = ast.FunctionDef(
                name=rest_name,
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=list(rest_stmts), decorator_list=[])
            # a later return-loop now lives inside the closure — convert
            # it there too (idempotent on already-rewritten loops)
            rest_fn = self.visit_FunctionDef(rest_fn)
            merge = ast.Return(value=_jst_call(
                "merge_return", [_name(ret), _name(rv), _name(rest_name)]))
            new_body = [*body[:idx], *pre, loop, rest_fn, merge]
            node.body = [ast.fix_missing_locations(
                ast.copy_location(st, s)) for st in new_body]
            break
        return node


class _ControlFlowTransformer(ast.NodeTransformer):
    """Rewrites If / While / BoolOp / Not into converter calls.

    ``if``/``while`` whose condition could be tensor-valued become closure
    pairs + a converter call; names assigned inside become the
    returned/threaded variables (the reference's ifelse/loop transformers).
    """

    def __init__(self):
        self.counter = 0
        self.prints = 0
        self.asserts = 0
        self._ret_flags = []

    # -- assert -----------------------------------------------------------
    def visit_Assert(self, node):
        self.generic_visit(node)
        self.asserts += 1
        # msg wrapped in a thunk: Python's assert evaluates the message
        # only on failure, and msg expressions may be failure-path-only
        # safe (or side-effectful)
        msg_args = []
        if node.msg:
            msg_args.append(ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[],
                                   kwonlyargs=[], kw_defaults=[],
                                   defaults=[]),
                body=node.msg))
        call = ast.Call(
            func=ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                               attr="convert_assert", ctx=ast.Load()),
            args=[node.test] + msg_args,
            keywords=[])
        return ast.copy_location(ast.Expr(value=call), node)

    # -- print ------------------------------------------------------------
    def visit_Call(self, node):
        self.generic_visit(node)
        # bare-name print(...) with plain args only (the reference's
        # print_transformer makes the same syntactic bet); starred/dict
        # splats keep Python semantics untouched
        if isinstance(node.func, ast.Name) and node.func.id == "print" \
                and not any(kw.arg is None for kw in node.keywords) \
                and not any(isinstance(a, ast.Starred) for a in node.args):
            self.prints += 1
            return ast.Call(
                func=ast.Attribute(
                    value=ast.Name(id=_JST, ctx=ast.Load()),
                    attr="convert_print", ctx=ast.Load()),
                args=node.args, keywords=node.keywords)
        return node

    # -- if/else ----------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        n = self.counter
        self.counter += 1
        body, orelse = node.body, node.orelse or [ast.Pass()]

        if _has(body + orelse, (ast.Break, ast.Continue)):
            # leave untouched: converter can't thread break/continue —
            # tracing will raise the helpful error if the pred is a tensor
            return node
        # returns are only convertible in the symmetric both-branches-end-
        # with-return form; ANY other return (nested in for/with/try, or
        # asymmetric) keeps Python semantics — a return inside a closure
        # would silently exit the closure instead of the function
        body_returns = isinstance(body[-1], ast.Return)
        else_returns = isinstance(orelse[-1], ast.Return)
        nested_returns = (_has(body[:-1] if body_returns else body,
                               ast.Return) or
                          _has(orelse[:-1] if else_returns else orelse,
                               ast.Return))
        if nested_returns or body_returns != else_returns:
            return node

        ret_name = f"__d2s_ret_{n}"
        if body_returns:
            body = [*body[:-1], ast.Assign(
                targets=[ast.Name(id=ret_name, ctx=ast.Store())],
                value=body[-1].value or ast.Constant(None))]
            orelse = [*orelse[:-1], ast.Assign(
                targets=[ast.Name(id=ret_name, ctx=ast.Store())],
                value=orelse[-1].value or ast.Constant(None))]

        assigned = _assigned_names(body + orelse)
        if body_returns:
            # the return-value carrier is generated (filtered by the
            # __d2s_ guard) but must thread through the branch closures
            assigned.append(ret_name)
        true_name, false_name = f"__d2s_true_{n}", f"__d2s_false_{n}"
        ret_tuple = ast.Tuple(
            elts=[ast.Name(id=a, ctx=ast.Load()) for a in assigned],
            ctx=ast.Load())

        def mkfn(name, stmts):
            # each assigned name becomes a defaulted parameter seeded from
            # the enclosing binding (the preamble guarantees it exists),
            # so a conditionally-bound name inside the closure can never
            # raise UnboundLocalError — it keeps its pre-if value, exactly
            # as the original straight-line code would
            return ast.FunctionDef(
                name=name,
                args=ast.arguments(
                    posonlyargs=[],
                    args=[ast.arg(arg=a) for a in assigned],
                    kwonlyargs=[], kw_defaults=[],
                    defaults=[ast.Name(id=a, ctx=ast.Load())
                              for a in assigned]),
                body=[*stmts, ast.Return(value=ret_tuple)],
                decorator_list=[])

        call = _jst_call("convert_ifelse",
                         [node.test,
                          ast.Name(id=true_name, ctx=ast.Load()),
                          ast.Name(id=false_name, ctx=ast.Load())])
        target = ast.Tuple(
            elts=[ast.Name(id=a, ctx=ast.Store()) for a in assigned],
            ctx=ast.Store())
        out = [*_preamble(assigned, n),
               mkfn(true_name, body), mkfn(false_name, orelse),
               ast.Assign(targets=[target], value=call)
               if assigned else ast.Expr(value=call)]
        if body_returns:
            out.append(ast.Return(value=ast.Name(id=ret_name,
                                                 ctx=ast.Load())))
        return [ast.fix_missing_locations(ast.copy_location(s, node))
                for s in out]

    # -- while ------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _has(node.body, (ast.Break, ast.Continue,
                                           ast.Return)):
            return node  # tracing will raise the guided error if needed
        n = self.counter
        self.counter += 1
        # loop state = names assigned in the body (they must pre-exist;
        # the preamble binds missing ones to the UNDEFINED sentinel and
        # the converter raises a named error on the traced path)
        loop_vars = _assigned_names(node.body)
        cond_name, body_name = f"__d2s_cond_{n}", f"__d2s_body_{n}"
        args = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=a) for a in loop_vars],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        ret_tuple = ast.Tuple(
            elts=[ast.Name(id=a, ctx=ast.Load()) for a in loop_vars],
            ctx=ast.Load())
        cond_fn = ast.FunctionDef(
            name=cond_name, args=args,
            body=[ast.Return(value=node.test)], decorator_list=[])
        body_fn = ast.FunctionDef(
            name=body_name, args=args,
            body=[*node.body, ast.Return(value=ret_tuple)],
            decorator_list=[])
        call = _jst_call(
            "convert_while_loop",
            [ast.Name(id=cond_name, ctx=ast.Load()),
             ast.Name(id=body_name, ctx=ast.Load()),
             ast.Tuple(elts=[ast.Name(id=a, ctx=ast.Load())
                             for a in loop_vars], ctx=ast.Load()),
             ast.Tuple(elts=[ast.Constant(a) for a in loop_vars],
                       ctx=ast.Load())])
        target = ast.Tuple(
            elts=[ast.Name(id=a, ctx=ast.Store()) for a in loop_vars],
            ctx=ast.Store())
        out = [*_preamble(loop_vars, n), cond_fn, body_fn,
               ast.Assign(targets=[target], value=call)
               if loop_vars else ast.Expr(value=call)]
        return [ast.fix_missing_locations(ast.copy_location(s, node))
                for s in out]

    # -- for over range() -------------------------------------------------
    def visit_For(self, node):
        """``for i in range(...)`` → while form via the shared
        ``_for_range_to_while`` desugar (reference: loop_transformer
        converts for→while); a tensor bound then lowers through
        convert_while_loop.  Non-range iterables (lists, LayerList,
        tensors) keep Python semantics — iterating a module list is the
        common case and must trace-unroll.

        After the loop the induction variable holds Python's
        last-yielded value (the hidden-iterator desugar); the sole
        divergence is an empty range, which leaves it at start instead
        of unbound."""
        self.generic_visit(node)
        if not (_is_range_for(node)
                and not _has(node.body, (ast.Break, ast.Continue,
                                         ast.Return))):
            return node
        # "c"-tagged stop/step names cannot collide with the exit
        # pre-pass's numeric tags
        init, test, bind_i, incr = _for_range_to_while(
            node, f"c{self.counter}")
        as_while = ast.While(test=test, body=[bind_i, *node.body, incr],
                             orelse=[])
        converted = self.visit_While(ast.copy_location(as_while, node))
        if converted is as_while:  # visit_While bailed (cannot happen for
            return node            # exit-free bodies, but stay safe)
        init = [ast.fix_missing_locations(ast.copy_location(s, node))
                for s in init]
        return [*init, *converted]

    # -- bool ops ---------------------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        expr = node.values[-1]
        for v in reversed(node.values[:-1]):
            thunk = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=expr)
            lhs_thunk = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=v)
            expr = _jst_call(fn, [lhs_thunk, thunk])
        return ast.copy_location(expr, node)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.copy_location(
                _jst_call("convert_logical_not", [node.operand]), node)
        return node


def convert_function(fn):
    """AST-convert ``fn``; returns the converted function or None when the
    source is unavailable/unconvertible (caller falls back to tracing)."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return None
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return None
    func_def = tree.body[0]
    if not isinstance(func_def, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    # only the to_static-family decorators may be stripped; any OTHER
    # decorator (autocast wrappers, caches...) would silently disappear
    # from the recompiled function — bail to the trace path instead
    for dec in func_def.decorator_list:
        name = dec
        while isinstance(name, (ast.Call, ast.Attribute)):
            name = name.func if isinstance(name, ast.Call) else name.attr
        dec_name = name if isinstance(name, str) else getattr(
            name, "id", "")
        if dec_name not in ("to_static", "declarative", "not_to_static"):
            return None
    func_def.decorator_list = []  # run once, undecorated
    if fn.__code__.co_freevars:
        # closures (including the implicit __class__ cell behind
        # zero-arg super()) cannot be faithfully rebuilt by exec — cells
        # would freeze to decoration-time snapshots and super() would
        # lose its cell entirely.  Fall back to the trace path.
        return None
    exits = _LoopExitTransformer()
    tree = exits.visit(tree)
    transformer = _ControlFlowTransformer()
    new_tree = transformer.visit(tree)
    if transformer.counter == 0 and transformer.prints == 0 \
            and transformer.asserts == 0 and not exits.changed:
        return None  # nothing to convert — tracing alone is enough
    ast.fix_missing_locations(new_tree)
    code = compile(new_tree, f"<dy2static:{fn.__qualname__}>", "exec")
    gl = dict(fn.__globals__)
    from . import dy2static as _self
    gl[_JST] = _self
    loc = {}
    exec(code, gl, loc)
    converted = loc[func_def.name]
    converted = functools.wraps(fn)(converted)
    converted.__wrapped_by_dy2static__ = True
    if fn.__defaults__:
        converted.__defaults__ = fn.__defaults__
    return converted
