"""paddle.onnx (reference: python/paddle/onnx/export.py, delegating to the
external paddle2onnx package).

The TPU build's portable artifact is StableHLO (paddle.jit.save), which is
what XLA-family runtimes consume; ONNX export would need an external
converter that is not vendored, so export() saves the StableHLO artifact
and says so rather than silently writing a different format.
"""
from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    from . import jit as jit_mod
    if path.endswith(".onnx"):
        path = path[:-len(".onnx")]
    jit_mod.save(layer, path, input_spec=input_spec)
    raise NotImplementedError(
        "ONNX serialization requires the external paddle2onnx converter "
        "(not available in this environment). The model WAS exported as a "
        f"portable StableHLO artifact at '{path}.pdmodel' — load it with "
        "paddle.jit.load or paddle.inference.Predictor.")
