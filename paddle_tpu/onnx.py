"""paddle.onnx (reference: python/paddle/onnx/export.py, which
delegates to the external paddle2onnx package).

Round 5: ``export`` is a REAL minimal ONNX exporter.  The reference
converts its ProgramDesc op-by-op; here the eval-mode forward is traced
to a jaxpr and each primitive maps to standard ONNX ops (opset 13) —
`Conv`, `MatMul`, `MaxPool`, elementwise, reductions, `Reshape`, … —
enough to cover the vision zoo (LeNet, ResNet, VGG-style stacks) and
any model lowering to the mapped primitive set.  Serialization uses a
protoc-compiled subset of the public ONNX schema
(``onnx_export/onnx_subset.proto`` — spec field numbers, so any ONNX
consumer parses the file); no external onnx package is needed.

Models using primitives outside the mapped set get an error naming the
primitive, with StableHLO (``paddle.jit.save``) as the full-coverage
portable artifact.
"""
from __future__ import annotations

import numpy as np

_ONNX_DTYPE = {"float32": 1, "uint8": 2, "int8": 3, "int16": 5,
               "int32": 6, "int64": 7, "bool": 9, "float16": 10,
               "float64": 11, "uint32": 12, "uint64": 13,
               "bfloat16": 16}


def _pb():
    from .onnx_export import onnx_subset_pb2 as P
    return P


class _Converter:
    """Walks a closed jaxpr, emitting ONNX nodes (one primitive may
    expand to several nodes).  Call-like primitives (pjit,
    custom_jvp/vjp, remat) are inlined recursively."""

    def __init__(self, graph, opset):
        self.g = graph
        self.opset = opset
        self._n = 0
        self.names = {}       # jax Var -> onnx name
        self._const_memo = {}  # (dtype, shape, bytes) -> initializer name

    # -- naming / constants ------------------------------------------------
    def fresh(self, hint="v"):
        self._n += 1
        return f"{hint}_{self._n}"

    def name_of(self, atom):
        if hasattr(atom, "val"):   # jax core Literal
            return self.add_const(np.asarray(atom.val))
        return self.names[atom]

    def add_const(self, arr, name=None):
        arr = np.asarray(arr)
        raw = np.ascontiguousarray(arr).tobytes()
        memo_key = None
        if name is None:
            # memoize unnamed constants by value: jaxprs repeat shape
            # vectors / scale scalars constantly, and emitting each as
            # its own initializer bloats the file with duplicates
            memo_key = (str(arr.dtype), arr.shape, raw)
            hit = self._const_memo.get(memo_key)
            if hit is not None:
                return hit
        name = name or self.fresh("const")
        t = self.g.initializer.add()
        t.name = name
        t.dims.extend(arr.shape)
        dt = _ONNX_DTYPE.get(str(arr.dtype))
        if dt is None:
            raise NotImplementedError(
                f"onnx.export: dtype {arr.dtype} has no ONNX mapping")
        t.data_type = dt
        t.raw_data = raw
        if memo_key is not None:
            self._const_memo[memo_key] = name
        return name

    def node(self, op, inputs, n_out=1, **attrs):
        P = _pb()
        nd = self.g.node.add()
        nd.op_type = op
        nd.name = self.fresh(op)
        nd.input.extend(inputs)
        outs = [self.fresh(op.lower()) for _ in range(n_out)]
        nd.output.extend(outs)
        for k, v in attrs.items():
            a = nd.attribute.add()
            a.name = k
            if isinstance(v, int):
                a.type = P.AttributeProto.INT
                a.i = v
            elif isinstance(v, float):
                a.type = P.AttributeProto.FLOAT
                a.f = v
            elif isinstance(v, str):
                a.type = P.AttributeProto.STRING
                a.s = v.encode()
            elif isinstance(v, (list, tuple)):
                a.type = P.AttributeProto.INTS
                a.ints.extend(int(x) for x in v)
            else:
                raise TypeError(f"attr {k}: {type(v)}")
        return outs if n_out > 1 else outs[0]

    # -- jaxpr walk --------------------------------------------------------
    def run(self, jaxpr, consts, in_names):
        for var, val in zip(jaxpr.constvars, consts):
            self.names[var] = self.add_const(np.asarray(val))
        for var, nm in zip(jaxpr.invars, in_names):
            self.names[var] = nm
        for eqn in jaxpr.eqns:
            self.eqn(eqn)
        return [self.name_of(v) for v in jaxpr.outvars]

    def inline(self, eqn, closed):
        inner = closed.jaxpr
        for var, val in zip(inner.constvars, closed.consts):
            self.names[var] = self.add_const(np.asarray(val))
        for var, outer in zip(inner.invars, eqn.invars):
            self.names[var] = self.name_of(outer)
        for sub in inner.eqns:
            self.eqn(sub)
        for outer, innerv in zip(eqn.outvars, inner.outvars):
            self.names[outer] = self.name_of(innerv)

    def _general_dot(self, eqn, ins):
        """Any dot_general as Transpose/Reshape/batched-MatMul/Reshape
        (jax result layout: batch dims, lhs free, rhs free — exactly
        what [B, F1, C] @ [B, C, F2] produces after regrouping)."""
        import numpy as _np
        ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
        lsh = eqn.invars[0].aval.shape
        rsh = eqn.invars[1].aval.shape
        lf = [i for i in range(len(lsh)) if i not in lc and i not in lb]
        rf = [i for i in range(len(rsh)) if i not in rc and i not in rb]
        B = int(_np.prod([lsh[i] for i in lb])) if lb else 1
        C = int(_np.prod([lsh[i] for i in lc])) if lc else 1
        F1 = int(_np.prod([lsh[i] for i in lf])) if lf else 1
        F2 = int(_np.prod([rsh[i] for i in rf])) if rf else 1

        def regroup(name, perm, shape3):
            t = self.node("Transpose", [name], perm=perm)
            shp = self.add_const(np.asarray(shape3, np.int64))
            return self.node("Reshape", [t, shp])

        a = regroup(ins[0], list(lb) + lf + list(lc), [B, F1, C])
        bb = regroup(ins[1], list(rb) + list(rc) + rf, [B, C, F2])
        mm = self.node("MatMul", [a, bb])
        out_shape = self.add_const(np.asarray(
            eqn.outvars[0].aval.shape, np.int64))
        return self.node("Reshape", [mm, out_shape])

    def _gather(self, eqn, ins):
        """jax gather in its embedding/take-along-axis-0 form -> ONNX
        Gather; anything fancier raises."""
        dn = eqn.params["dimension_numbers"]
        op_shape = eqn.invars[0].aval.shape
        slice_sizes = tuple(eqn.params["slice_sizes"])
        ok = (tuple(dn.start_index_map) == (0,)
              and tuple(dn.collapsed_slice_dims) == (0,)
              and slice_sizes[0] == 1
              and slice_sizes[1:] == tuple(op_shape[1:]))
        if not ok:
            raise NotImplementedError(
                "onnx.export: general gather (only take-along-axis-0 / "
                "embedding-style gathers map to Gather) — use StableHLO "
                "export")
        # jax index operand carries a trailing index-vector dim of 1
        idx_shape = eqn.invars[1].aval.shape
        idx = ins[1]
        if idx_shape and idx_shape[-1] == 1:
            shp = self.add_const(np.asarray(idx_shape[:-1], np.int64))
            idx = self.node("Reshape", [idx, shp])
        idx64 = self.node("Cast", [idx], to=7)  # Gather wants int64/32
        return self.node("Gather", [ins[0], idx64], axis=0)

    _ELEMENTWISE = {
        "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
        "max": "Max", "min": "Min", "pow": "Pow", "sqrt": "Sqrt",
        "exp": "Exp", "log": "Log", "tanh": "Tanh",
        "logistic": "Sigmoid", "abs": "Abs", "neg": "Neg",
        "sign": "Sign", "floor": "Floor", "ceil": "Ceil",
    }
    _COMPARE = {"gt": "Greater", "lt": "Less", "ge": "GreaterOrEqual",
                "le": "LessOrEqual", "eq": "Equal"}
    # jax reuses and/or/xor/not for BITWISE integer ops; ONNX
    # And/Or/Xor/Not are bool-only, so the mapping is dtype-gated
    _LOGICAL = {"and": "And", "or": "Or", "xor": "Xor", "not": "Not"}
    _REDUCE_ATTR = {"reduce_max": "ReduceMax", "reduce_min": "ReduceMin",
                    "reduce_prod": "ReduceProd"}

    def eqn(self, eqn):
        p = str(eqn.primitive)
        params = eqn.params
        ins = [self.name_of(a) for a in eqn.invars]

        def out(name):
            self.names[eqn.outvars[0]] = name

        if p in ("pjit", "jit", "closed_call", "core_call",
                 "remat", "checkpoint"):
            return self.inline(eqn, params["jaxpr"])
        if p in ("custom_jvp_call", "custom_vjp_call"):
            return self.inline(eqn, params["call_jaxpr"])

        if p in self._ELEMENTWISE:
            return out(self.node(self._ELEMENTWISE[p], ins))
        if p in self._COMPARE:
            return out(self.node(self._COMPARE[p], ins))
        if p in self._LOGICAL:
            if any(str(v.aval.dtype) != "bool" for v in eqn.invars):
                raise NotImplementedError(
                    f"onnx.export: bitwise integer '{p}' has no "
                    "opset-13 mapping (ONNX And/Or/Xor/Not are "
                    "bool-only) — use StableHLO export")
            return out(self.node(self._LOGICAL[p], ins))
        if p == "ne":
            eq_out = self.node("Equal", ins)
            return out(self.node("Not", [eq_out]))
        if p == "select_n":
            if len(ins) != 3:
                raise NotImplementedError(
                    "onnx.export: select_n with >2 cases")
            # select_n(pred, on_false, on_true) -> Where(pred, T, F)
            return out(self.node("Where", [ins[0], ins[2], ins[1]]))
        if p == "integer_pow":
            y = int(params["y"])
            if y == 2:
                return out(self.node("Mul", [ins[0], ins[0]]))
            e = self.add_const(np.asarray(float(y), np.float32))
            return out(self.node("Pow", [ins[0], e]))
        if p == "rsqrt":
            s = self.node("Sqrt", ins)
            return out(self.node("Reciprocal", [s]))
        if p == "convert_element_type":
            key = str(np.dtype(params["new_dtype"]))
            dt = _ONNX_DTYPE.get(key)
            if dt is None:
                raise NotImplementedError(
                    f"onnx.export: cast to {key} has no ONNX mapping — "
                    "use StableHLO export (paddle.jit.save)")
            return out(self.node("Cast", ins, to=dt))
        if p == "stop_gradient":
            return out(self.node("Identity", ins))
        if p in ("reshape", "squeeze", "expand_dims"):
            if p == "reshape" and params.get("dimensions") is not None:
                raise NotImplementedError(
                    "onnx.export: reshape with dimensions (fused "
                    "transpose)")
            shp = self.add_const(np.asarray(
                eqn.outvars[0].aval.shape, np.int64))
            return out(self.node("Reshape", [ins[0], shp]))
        if p == "transpose":
            return out(self.node("Transpose", ins,
                                 perm=list(params["permutation"])))
        if p == "broadcast_in_dim":
            tgt = list(params["shape"])
            bdims = list(params["broadcast_dimensions"])
            interm = [1] * len(tgt)
            for src_axis, dst_axis in enumerate(bdims):
                interm[dst_axis] = eqn.invars[0].aval.shape[src_axis]
            shp = self.add_const(np.asarray(interm, np.int64))
            r = self.node("Reshape", [ins[0], shp])
            tgt_c = self.add_const(np.asarray(tgt, np.int64))
            return out(self.node("Expand", [r, tgt_c]))
        if p == "concatenate":
            return out(self.node("Concat", ins,
                                 axis=int(params["dimension"])))
        if p == "slice":
            if params.get("strides") is None:
                strides = [1] * len(params["start_indices"])
            else:
                strides = list(params["strides"])
            st = self.add_const(np.asarray(params["start_indices"],
                                           np.int64))
            en = self.add_const(np.asarray(params["limit_indices"],
                                           np.int64))
            ax = self.add_const(np.arange(len(strides),
                                          dtype=np.int64))
            sp = self.add_const(np.asarray(strides, np.int64))
            return out(self.node("Slice", [ins[0], st, en, ax, sp]))
        if p == "reduce_sum":
            axes = self.add_const(np.asarray(params["axes"], np.int64))
            return out(self.node("ReduceSum", [ins[0], axes],
                                 keepdims=0))
        if p in self._REDUCE_ATTR:
            return out(self.node(self._REDUCE_ATTR[p], ins,
                                 axes=list(params["axes"]),
                                 keepdims=0))
        if p == "square":
            return out(self.node("Mul", [ins[0], ins[0]]))
        if p == "erf":
            return out(self.node("Erf", ins))
        if p == "erfc":
            e = self.node("Erf", ins)
            one = self.add_const(np.asarray(
                1.0, np.dtype(eqn.invars[0].aval.dtype)))
            return out(self.node("Sub", [one, e]))
        if p == "gather":
            return out(self._gather(eqn, ins))
        if p == "dot_general":
            ((lc, rc), (lb, rb)) = params["dimension_numbers"]
            lhs_nd = len(eqn.invars[0].aval.shape)
            simple = (list(lb) == list(range(len(lb)))
                      and list(rb) == list(range(len(rb)))
                      and list(lc) == [lhs_nd - 1]
                      and list(rc) == [len(lb)])
            if simple:
                return out(self.node("MatMul", ins))
            return out(self._general_dot(eqn, ins))
        if p == "conv_general_dilated":
            dn = params["dimension_numbers"]
            if (dn.lhs_spec != (0, 1, 2, 3)
                    or dn.rhs_spec != (0, 1, 2, 3)
                    or dn.out_spec != (0, 1, 2, 3)):
                raise NotImplementedError(
                    "onnx.export: only NCHW/OIHW convolutions map to "
                    f"Conv (got {dn})")
            if any(d != 1 for d in params["lhs_dilation"]):
                raise NotImplementedError(
                    "onnx.export: lhs_dilation (transposed conv) — "
                    "use StableHLO export")
            pads = list(params["padding"])
            kshape = eqn.invars[1].aval.shape[2:]
            return out(self.node(
                "Conv", ins,
                strides=list(params["window_strides"]),
                dilations=list(params["rhs_dilation"]),
                group=int(params["feature_group_count"]),
                kernel_shape=list(kshape),
                pads=[pads[0][0], pads[1][0], pads[0][1], pads[1][1]]))
        if p in ("reduce_window_max", "reduce_window_sum"):
            wd = list(params["window_dimensions"])
            ws = list(params["window_strides"])
            pads = list(params["padding"])
            if (len(wd) != 4 or wd[0] != 1 or wd[1] != 1
                    or ws[0] != 1 or ws[1] != 1
                    or pads[0] != (0, 0) or pads[1] != (0, 0)):
                raise NotImplementedError(
                    "onnx.export: reduce_window with windows/strides/"
                    "padding on batch or channel dims (only NCHW "
                    "spatial pooling maps to Max/AveragePool)")
            if any(d != 1 for d in params.get("window_dilation",
                                              (1,) * len(wd))):
                raise NotImplementedError(
                    "onnx.export: dilated pooling windows")
            if any(d != 1 for d in params.get("base_dilation",
                                              (1,) * len(wd))):
                raise NotImplementedError(
                    "onnx.export: base_dilation in reduce_window — use "
                    "StableHLO export")
            kw = dict(kernel_shape=wd[2:], strides=ws[2:],
                      pads=[pads[2][0], pads[3][0],
                            pads[2][1], pads[3][1]])
            if p == "reduce_window_max":
                return out(self.node("MaxPool", ins, **kw))
            # the scale constant must match the TENSOR dtype: a float32
            # scalar against a float64/float16 AveragePool output makes
            # the Mul operands mismatch — an invalid model with no
            # export-time error
            in_dtype = np.dtype(eqn.invars[0].aval.dtype)
            if in_dtype.kind != "f":
                raise NotImplementedError(
                    f"onnx.export: sum-pooling over {in_dtype} — "
                    "AveragePool (the Mul-rescaled lowering) is "
                    "float-only; use StableHLO export")
            ap = self.node("AveragePool", ins,
                           count_include_pad=1, **kw)
            scale = self.add_const(
                np.asarray(float(wd[2] * wd[3]), in_dtype))
            return out(self.node("Mul", [ap, scale]))
        if p == "iota":
            aval = eqn.outvars[0].aval
            arr = np.arange(aval.shape[params["dimension"]])
            full = np.broadcast_to(
                arr.reshape([-1 if i == params["dimension"] else 1
                             for i in range(len(aval.shape))]),
                aval.shape).astype(np.dtype(params["dtype"]))
            return out(self.add_const(full))
        if p == "copy":
            return out(self.node("Identity", ins))

        raise NotImplementedError(
            f"onnx.export: primitive '{p}' has no ONNX mapping yet — "
            "the full-coverage portable artifact is StableHLO "
            "(paddle.jit.save / paddle.inference)")


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Export ``layer``'s eval forward as a real ONNX model.

    ``input_spec``: list of InputSpec/arrays with STATIC shapes (the
    jaxpr trace fixes them; for batch-polymorphic artifacts use
    paddle.jit.save's StableHLO path).  Writes ``path`` (``.onnx``
    appended if absent) and returns the path.
    """
    import jax
    from .core.tensor import Tensor
    from .static import InputSpec

    P = _pb()
    if int(opset_version) < 13:
        raise ValueError(
            f"onnx.export: opset_version={opset_version} — the emitted "
            "node forms (ReduceSum-with-axes-input, 5-input Slice, "
            "Where, GreaterOrEqual) require opset >= 13; pass "
            "opset_version=13 (the default)")
    if not path.endswith(".onnx"):
        path = path + ".onnx"
    if input_spec is None:
        raise ValueError("onnx.export requires input_spec")

    examples = []
    for spec in input_spec:
        if isinstance(spec, InputSpec):
            if any(d is None or (isinstance(d, int) and d < 0)
                   for d in spec.shape):
                raise ValueError(
                    "onnx.export: dynamic dims are not supported by "
                    "the minimal exporter — give static shapes, or "
                    "use paddle.jit.save (StableHLO) for "
                    "batch-polymorphic artifacts")
            examples.append(np.zeros(spec.shape,
                                     spec.dtype or "float32"))
        else:
            examples.append(np.asarray(spec))

    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    try:
        def fn(*arrays):
            outs = layer(*[Tensor(a) for a in arrays])
            if isinstance(outs, (list, tuple)):
                return [o._data if isinstance(o, Tensor) else o
                        for o in outs]
            return outs._data if isinstance(outs, Tensor) else outs

        closed = jax.make_jaxpr(fn)(*examples)
    finally:
        if was_training and hasattr(layer, "train"):
            layer.train()

    model = P.ModelProto()
    model.ir_version = 7
    model.producer_name = "paddle_tpu"
    op = model.opset_import.add()
    op.domain = ""
    op.version = int(opset_version)
    g = model.graph
    g.name = type(layer).__name__

    in_names = []
    for i, ex in enumerate(examples):
        nm = f"input_{i}"
        in_names.append(nm)
        vi = g.input.add()
        vi.name = nm
        dt = _ONNX_DTYPE.get(str(ex.dtype))
        if dt is None:
            raise NotImplementedError(
                f"onnx.export: input dtype {ex.dtype} has no ONNX "
                "mapping — use StableHLO export (paddle.jit.save)")
        vi.type.tensor_type.elem_type = dt
        for d in ex.shape:
            vi.type.tensor_type.shape.dim.add().dim_value = d

    conv = _Converter(g, opset_version)
    out_names = conv.run(closed.jaxpr, closed.consts, in_names)

    # dead-code elimination: jaxprs can carry unconsumed results (e.g.
    # extra outputs of inlined custom_jvp bodies); keep only nodes and
    # initializers reachable from the graph outputs
    needed = set(out_names)
    keep_nodes = []
    for nd in reversed(list(g.node)):
        if any(o in needed for o in nd.output):
            keep_nodes.append(nd)
            needed.update(nd.input)
    del g.node[:]
    for nd in reversed(keep_nodes):
        g.node.add().CopyFrom(nd)
    keep_init = [t for t in g.initializer if t.name in needed]
    del g.initializer[:]
    for t in keep_init:
        g.initializer.add().CopyFrom(t)

    for nm, var in zip(out_names, closed.jaxpr.outvars):
        vo = g.output.add()
        vo.name = nm
        aval = var.aval
        vo.type.tensor_type.elem_type = _ONNX_DTYPE[str(aval.dtype)]
        for d in aval.shape:
            vo.type.tensor_type.shape.dim.add().dim_value = int(d)

    with open(path, "wb") as f:
        f.write(model.SerializeToString())
    return path
