"""Runtime stat registry.

Reference parity: ``platform/monitor.h`` — ``StatValue<T>`` (thread-safe
increase/decrease/reset counters) registered in a name-keyed
``StatRegistry`` singleton and bumped via ``STAT_ADD``/``STAT_SUB``
macros (GPU mem stats etc., exported to Python through pybind).

TPU-native extension: the reference had "no Prometheus/OpenTelemetry-
style exporter in-tree" (SURVEY §5.5); a serving system needs one, so
the registry grows Prometheus-flavored metric types (Counter / Gauge /
Histogram) and a text exposition renderer (exposition.py).  Everything
is pure stdlib + threads — no jax import, so DataLoader worker
processes and the HTTP metrics handler can use it freely.
"""
from __future__ import annotations

import re
import threading
import time


class Counter:
    """Monotonically increasing count (Prometheus counter)."""

    kind = "counter"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n=1):
        if n < 0:
            raise ValueError(
                f"Counter {self.name!r} is monotonic; inc({n}) would "
                "decrease it (use a Gauge for up/down values)")
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value

    def reset(self):
        with self._lock:
            self._value = 0.0


class Gauge:
    """Instantaneous value that can go up or down (Prometheus gauge)."""

    kind = "gauge"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v):
        with self._lock:
            self._value = float(v)

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        with self._lock:
            self._value -= n

    @property
    def value(self):
        with self._lock:
            return self._value

    def reset(self):
        with self._lock:
            self._value = 0.0


# Latency-shaped default buckets (seconds-as-milliseconds friendly):
# spans sub-ms jit dispatch to multi-second prefill/compile outliers.
DEFAULT_BUCKETS = (0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
                   1000, 2500, 5000, 10000)


class Histogram:
    """Cumulative-bucket histogram (Prometheus histogram semantics:
    each ``le`` bucket counts observations <= its bound, plus +Inf)."""

    kind = "histogram"

    def __init__(self, name, help="", buckets=DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("Histogram needs at least one bucket bound")
        self.bounds = bounds
        self._lock = threading.Lock()
        self._counts = [0] * (len(bounds) + 1)  # last = +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, v):
        v = float(v)
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.bounds):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def snapshot(self):
        """(cumulative_bucket_counts aligned to bounds + +Inf, sum,
        count) — cumulative per Prometheus exposition rules."""
        with self._lock:
            raw = list(self._counts)
            total, cum = 0, []
            for c in raw:
                total += c
                cum.append(total)
            return cum, self._sum, self._count

    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def mean(self):
        with self._lock:
            return self._sum / self._count if self._count else 0.0

    def percentile(self, q):
        """Estimate the q-th percentile (``q`` in [0, 100]) from the
        bucket counts, linearly interpolating within the containing
        bucket (Prometheus ``histogram_quantile`` semantics: the first
        bucket interpolates up from 0, and a rank landing in the +Inf
        overflow bucket returns the highest finite bound — the
        histogram cannot resolve beyond it).  NaN on an empty
        histogram.  Bench and tests use this to assert latency bounds
        (e.g. TPOT p99) without a Prometheus server."""
        if not 0 <= q <= 100:
            raise ValueError(f"percentile q must be in [0, 100], got {q}")
        with self._lock:
            counts = list(self._counts)
            total = self._count
        if total == 0:
            return float("nan")
        rank = q / 100.0 * total
        cum, lo = 0, 0.0
        for bound, c in zip(self.bounds, counts):
            if c > 0 and cum + c >= rank:
                frac = min(max((rank - cum) / c, 0.0), 1.0)
                return lo + (bound - lo) * frac
            cum += c
            lo = bound
        return self.bounds[-1]

    def reset(self):
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._sum = 0.0
            self._count = 0


class StatValue:
    """reference: platform/monitor.h:30 StatValue<T> — a thread-safe
    int stat with increase/decrease/reset, bumped via stat_add/stat_sub
    (the STAT_ADD/STAT_SUB macro twins)."""

    kind = "stat"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0

    def increase(self, n=1):
        with self._lock:
            self._value += n
            return self._value

    def decrease(self, n=1):
        with self._lock:
            self._value -= n
            return self._value

    def reset(self):
        with self._lock:
            self._value = 0

    def get(self):
        with self._lock:
            return self._value

    @property
    def value(self):
        return self.get()


class StatRegistry:
    """Name-keyed metric registry (reference: monitor.h:77
    StatRegistry::Instance).  ``counter()``/``gauge()``/``histogram()``/
    ``stat()`` are get-or-create; asking for an existing name with a
    different metric type is a loud error, never a silent shadow."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def _get_or_create(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help=help, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name, help=""):
        return self._get_or_create(Counter, name, help)

    def gauge(self, name, help=""):
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name, help="", buckets=DEFAULT_BUCKETS):
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def stat(self, name, help=""):
        return self._get_or_create(StatValue, name, help)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name):
        with self._lock:
            self._metrics.pop(name, None)

    def items(self):
        """Point-in-time snapshot of (name, metric) pairs, sorted,
        taken under ONE lock acquisition.  This is the exposition
        contract: ``render_prometheus`` iterates the returned LIST, so
        a metric registered concurrently (e.g. the engine's
        compile-event hook firing while a /metrics or /debug handler
        renders) can never mutate the mapping mid-iteration — it
        simply appears in the next render."""
        with self._lock:
            return sorted(self._metrics.items())

    def reset(self):
        """Zero every metric, keeping registrations (test isolation)."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.reset()

    def clear(self):
        with self._lock:
            self._metrics.clear()


_default = StatRegistry()


def default_registry():
    return _default


# -- reference-macro twins (monitor.h:130 STAT_ADD/STAT_SUB) -------------

def stat_add(name, n=1):
    """STAT_ADD: bump the named int stat in the default registry."""
    return _default.stat(name).increase(n)


def stat_sub(name, n=1):
    """STAT_SUB twin of stat_add."""
    return _default.stat(name).decrease(n)


def stat_get(name):
    """Read the named int stat (0 if never touched — matching the
    reference's default-constructed StatValue)."""
    m = _default.get(name)
    return m.get() if isinstance(m, StatValue) else 0


_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_name(name):
    """Map internal dotted names ('serving.queue_depth') onto the
    Prometheus charset ([a-zA-Z_:][a-zA-Z0-9_:]*)."""
    out = _NAME_RE.sub("_", name)
    if not out or out[0].isdigit():
        out = "_" + out
    return out


class RateMeter:
    """Windowed events-per-second meter (tokens/sec and friends): feeds
    a Gauge from a monotonic-clock window so the value stays meaningful
    without a Prometheus server computing rate() over a Counter."""

    def __init__(self, gauge, window_s=2.0):
        self.gauge = gauge
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._events = []  # (t, n)

    def add(self, n, now=None):
        now = time.monotonic() if now is None else now
        with self._lock:
            self._events.append((now, n))
            self._update(now)

    def refresh(self, now=None):
        """Re-evaluate the window without an event: an idle producer
        must decay the gauge to 0, not freeze the last burst's rate
        forever (call from the producer's idle loop)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._update(now)

    def _update(self, now):
        cutoff = now - self.window_s
        while self._events and self._events[0][0] < cutoff:
            self._events.pop(0)
        if not self._events:
            self.gauge.set(0.0)
            return
        total = sum(k for _, k in self._events)
        span = max(now - self._events[0][0], 1e-6)
        # span < window right after start; dividing by the true span
        # avoids the cold-start underestimate
        self.gauge.set(total / max(span, self.window_s / 10))
