"""paddle_tpu.monitor — runtime counters/gauges/histograms + Prometheus
text exposition.

Reference parity: ``platform/monitor.h`` ``StatValue``/``StatRegistry``
(+ the STAT_ADD/STAT_SUB macros) — see stats.py.  Consumers: the
serving engine (queue depth, slot occupancy, tokens/sec, TTFT/TPOT),
the compiled train step (step counters/latency), and the DataLoader
worker pool (batches consumed).  Pure stdlib — safe in fork'd worker
processes and HTTP handler threads; no jax import.
"""
from .stats import (  # noqa: F401
    Counter, Gauge, Histogram, StatValue, StatRegistry, RateMeter,
    DEFAULT_BUCKETS, default_registry, sanitize_name,
    stat_add, stat_sub, stat_get,
)
from .exposition import render_prometheus  # noqa: F401


def counter(name, help=""):
    """Get-or-create a Counter in the default registry."""
    return default_registry().counter(name, help)


def gauge(name, help=""):
    """Get-or-create a Gauge in the default registry."""
    return default_registry().gauge(name, help)


def histogram(name, help="", buckets=DEFAULT_BUCKETS):
    """Get-or-create a Histogram in the default registry."""
    return default_registry().histogram(name, help, buckets=buckets)


__all__ = [
    "Counter", "Gauge", "Histogram", "StatValue", "StatRegistry",
    "RateMeter", "DEFAULT_BUCKETS", "default_registry", "sanitize_name",
    "stat_add", "stat_sub", "stat_get", "render_prometheus",
    "counter", "gauge", "histogram",
]
