"""paddle_tpu.monitor — runtime counters/gauges/histograms + Prometheus
text exposition + the tick-level span tracer.

Reference parity: ``platform/monitor.h`` ``StatValue``/``StatRegistry``
(+ the STAT_ADD/STAT_SUB macros) — see stats.py — and
``platform/profiler.h`` ``RecordEvent`` spans with the
``tools/timeline.py`` chrome-trace export — see tracing.py (bounded
per-thread ring buffers, Catapult-native events, the serving engine's
flight recorder).  Consumers: the serving engine (queue depth, slot
occupancy, tokens/sec, TTFT/TPOT, tick spans), the compiled train
step (step counters/latency), and the DataLoader worker pool (batches
consumed).  Pure stdlib — safe in fork'd worker processes and HTTP
handler threads; no jax import (TraceAnnotation pass-through imports
jax lazily, only when asked for).
"""
from .stats import (  # noqa: F401
    Counter, Gauge, Histogram, StatValue, StatRegistry, RateMeter,
    DEFAULT_BUCKETS, default_registry, sanitize_name,
    stat_add, stat_sub, stat_get,
)
from .exposition import render_prometheus  # noqa: F401
from .tracing import (  # noqa: F401
    Tracer, NullTracer, RecordEvent, TraceEvent, to_chrome_trace,
    default_tracer,
)


def counter(name, help=""):
    """Get-or-create a Counter in the default registry."""
    return default_registry().counter(name, help)


def gauge(name, help=""):
    """Get-or-create a Gauge in the default registry."""
    return default_registry().gauge(name, help)


def histogram(name, help="", buckets=DEFAULT_BUCKETS):
    """Get-or-create a Histogram in the default registry."""
    return default_registry().histogram(name, help, buckets=buckets)


__all__ = [
    "Counter", "Gauge", "Histogram", "StatValue", "StatRegistry",
    "RateMeter", "DEFAULT_BUCKETS", "default_registry", "sanitize_name",
    "stat_add", "stat_sub", "stat_get", "render_prometheus",
    "counter", "gauge", "histogram",
    "Tracer", "NullTracer", "RecordEvent", "TraceEvent",
    "to_chrome_trace", "default_tracer",
]
