"""Tick-level span tracer + flight-recorder buffer.

Reference parity: ``platform/profiler.h`` ``RecordEvent`` (RAII host
spans) collected per thread and exported through
``tools/timeline.py`` as a chrome://tracing (Catapult JSON) timeline.
The reproduction's twin is serving-shaped: the ``Tracer`` keeps a
BOUNDED ring buffer of complete-events per thread — cheap enough to
leave on in production — so the last N engine ticks are always
retained, and a crash can dump them as a post-mortem "flight
recorder" (serving/engine.py wires this into its step-failure
recovery path; ``/debug/trace`` serves the live buffer).

Design points:

- **Low overhead.**  A span is two ``time.perf_counter()`` calls and
  one deque append under a lock; a disabled tracer (or the
  ``NullTracer``) short-circuits to a shared no-op context manager.
  No jax import at module level — like the rest of ``monitor``, this
  is pure stdlib and safe in fork'd workers and HTTP handler threads.
- **Thread-aware.**  Each thread appends into its own
  ``deque(maxlen=capacity)`` ring buffer, so the engine loop, HTTP
  handlers, and background threads never interleave events;
  ``events()`` merges the per-thread rings into one ts-sorted
  snapshot.
- **Chrome-trace native.**  Events are stored directly in Catapult
  complete-event shape (``ph="X"``, microsecond ``ts``/``dur``) plus
  instant events (``ph="i"``) for point-in-time lifecycle marks, so
  export is a dict build, not a format conversion.
- **XPlane pass-through.**  ``annotate=True`` (per tracer or per
  span) additionally enters a ``jax.profiler.TraceAnnotation`` so the
  same spans land in XPlane/TensorBoard captures when one is active
  (lazy jax import — only paid when asked for).
"""
from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque

# Catapult instant-event scope: "t" = thread-scoped tick mark (the
# narrow arrow in chrome://tracing), vs "p"/"g" process/global.
_INSTANT_SCOPE = "t"


class TraceEvent:
    """One trace event in Catapult terms: ``ph="X"`` complete event
    (ts + dur) or ``ph="i"`` instant.  ``ts``/``dur`` are microseconds
    on the ``time.perf_counter`` clock (monotonic; arbitrary origin,
    like the reference profiler's host timeline)."""

    __slots__ = ("name", "ph", "ts", "dur", "tid", "cat", "args")

    def __init__(self, name, ph, ts, dur, tid, cat, args):
        self.name = name
        self.ph = ph
        self.ts = ts
        self.dur = dur
        self.tid = tid
        self.cat = cat
        self.args = args

    def to_json(self, pid=None):
        d = {"name": self.name, "ph": self.ph, "pid": int(
            os.getpid() if pid is None else pid), "tid": self.tid,
            "ts": self.ts, "cat": self.cat}
        if self.ph == "X":
            d["dur"] = self.dur
        elif self.ph == "i":
            d["s"] = _INSTANT_SCOPE
        if self.args:
            d["args"] = self.args
        return d

    def __repr__(self):
        return (f"TraceEvent({self.name!r}, ph={self.ph!r}, "
                f"ts={self.ts:.1f}, dur={self.dur:.1f}, "
                f"tid={self.tid})")


class RecordEvent:
    """RAII span, mirroring the reference ``platform::RecordEvent``:
    usable as a context manager or a decorator.

        with RecordEvent("tick", tracer, batch=4) as sp:
            ...
            sp.args["emitted"] = n     # args may be amended pre-exit

        @RecordEvent("load_batch", tracer)
        def load_batch(...): ...

    Exactly two clock reads per span (enter + exit) — the elapsed
    seconds land on ``.elapsed`` and the complete-event is appended to
    the tracer's ring buffer.  ``annotate=True`` additionally wraps
    the span in ``jax.profiler.TraceAnnotation`` so it shows up in
    XPlane captures (requires jax; lazily imported)."""

    def __init__(self, name, tracer=None, cat="serving", annotate=None,
                 **args):
        self.name = name
        self._tracer = tracer if tracer is not None else default_tracer()
        self.cat = cat
        self.args = args
        tr_ann = getattr(self._tracer, "annotate", False)
        self._annotate = tr_ann if annotate is None else annotate
        self._ann = None
        self.elapsed = 0.0

    def __enter__(self):
        if self._annotate:
            import jax
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        self.elapsed = t1 - self._t0
        self._tracer._append(
            self.name, "X", self._t0 * 1e6, self.elapsed * 1e6,
            self.cat, self.args or None)
        if self._ann is not None:
            self._ann.__exit__(*exc)
            self._ann = None
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapped(*a, **kw):
            # fresh args dict per call: the decorator form is reused
            # across calls, and a shared mutable dict would leak one
            # call's annotations into the next event
            with RecordEvent(self.name, self._tracer, cat=self.cat,
                             annotate=self._annotate,
                             **dict(self.args)):
                return fn(*a, **kw)
        return wrapped


class _NullSpan:
    """Shared no-op span for disabled tracing: supports the same
    ``with ... as sp: sp.args[...] = ...`` protocol at near-zero cost
    (the args dict is written but never read; keys are bounded by the
    instrumentation sites, so it cannot grow without bound)."""

    __slots__ = ()
    args = {}
    elapsed = 0.0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __call__(self, fn):
        return fn


_NULL_SPAN = _NullSpan()


class NullTracer:
    """Drop-in disabled tracer (``Engine(tracing=False)``): every hook
    is a no-op, exports are empty — the instrumented hot paths pay one
    attribute call and nothing else."""

    enabled = False
    annotate = False

    def span(self, name, cat="serving", annotate=None, **args):
        return _NULL_SPAN

    def instant(self, name, cat="serving", **args):
        pass

    def emit(self, name, ts_s, dur_s, cat="serving", args=None):
        pass

    def _append(self, *a, **k):
        pass

    def events(self):
        return []

    def clear(self):
        pass

    def chrome_trace(self, process_name="paddle_tpu"):
        return {"traceEvents": [], "displayTimeUnit": "ms"}

    def dump(self, path, process_name="paddle_tpu"):
        with open(path, "w") as f:
            json.dump(self.chrome_trace(process_name), f)
        return path


class Tracer:
    """Thread-aware span collector over bounded per-thread ring
    buffers.

    ``capacity`` bounds EACH thread's ring (oldest events fall off —
    that is the flight-recorder property: under sustained load the
    buffer always holds the most recent ~capacity events, never grows,
    and never needs draining).  Lanes are per thread LIFETIME, not per
    OS thread id: each thread gets a fresh lane id on its first event
    (resolved through a ``threading.local``), so a recycled pthread
    ident can never write into — or inherit the label of — a dead
    handler thread's lane.  Dead threads' lanes are retained (their
    recent lifecycle events are exactly what a post-mortem wants)
    until the lane count exceeds ``max_threads``, then pruned oldest
    first — live lanes are never evicted.  ``enabled=False`` mutes
    collection without tearing down the buffers; flip ``enabled``
    freely at runtime (profiler start/stop does)."""

    def __init__(self, capacity=16384, enabled=True, annotate=False,
                 max_threads=64):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if max_threads < 1:
            raise ValueError(
                f"max_threads must be >= 1, got {max_threads}")
        self.capacity = int(capacity)
        self.max_threads = int(max_threads)
        self.enabled = bool(enabled)
        self.annotate = bool(annotate)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._buffers = {}       # lane -> deque(maxlen=capacity)
        self._thread_names = {}  # lane -> thread name at first event
        self._thread_refs = {}   # lane -> weakref to the thread
        self._next_lane = 1

    # -- collection ----------------------------------------------------
    def _buf(self):
        cached = getattr(self._local, "lane_buf", None)
        if cached is not None:
            return cached
        t = threading.current_thread()
        with self._lock:
            self._prune_dead_locked()
            lane = self._next_lane
            self._next_lane += 1
            buf = deque(maxlen=self.capacity)
            self._buffers[lane] = buf
            self._thread_names[lane] = t.name
            self._thread_refs[lane] = weakref.ref(t)
        self._local.lane_buf = (lane, buf)
        return lane, buf

    def _prune_dead_locked(self):
        """Bound the lane table: once ``max_threads`` lanes exist,
        evict DEAD threads' lanes in creation order until back under
        the bound (short-lived HTTP handler threads each burn a lane;
        without this a thread-per-connection server grows the table
        forever).  Caller holds the lock."""
        if len(self._buffers) < self.max_threads:
            return
        for lane in list(self._buffers):
            if len(self._buffers) < self.max_threads:
                break
            th = self._thread_refs[lane]()
            if th is None or not th.is_alive():
                del self._buffers[lane]
                del self._thread_names[lane]
                del self._thread_refs[lane]

    def _append(self, name, ph, ts_us, dur_us, cat, args):
        if not self.enabled:
            return
        tid, buf = self._buf()
        # the lock covers the append/snapshot race: deque.append is
        # atomic, but ``events()`` listing a ring mid-append from
        # another thread would raise "deque mutated during iteration"
        with self._lock:
            buf.append(TraceEvent(name, ph, ts_us, dur_us, tid, cat,
                                  dict(args) if args else None))

    def span(self, name, cat="serving", annotate=None, **args):
        """Open a complete-event span (context manager / decorator).
        Keyword args become the event's chrome-trace ``args``; amend
        ``sp.args`` inside the block for values only known at exit."""
        if not self.enabled:
            return _NULL_SPAN
        return RecordEvent(name, self, cat=cat, annotate=annotate,
                           **args)

    def instant(self, name, cat="serving", **args):
        """Record a point-in-time instant event (``ph="i"``) — the
        per-request lifecycle marks (queued/admitted/first-token/...)."""
        if not self.enabled:
            return
        self._append(name, "i", time.perf_counter() * 1e6, 0.0, cat,
                     args or None)

    def emit(self, name, ts_s, dur_s, cat="serving", args=None):
        """Append a complete-event measured externally (seconds on the
        perf_counter clock) — the compile-event hook uses this: the
        wall time was measured around the first jitted call, the event
        is back-dated to when it started."""
        self._append(name, "X", ts_s * 1e6, dur_s * 1e6, cat, args)

    # -- snapshot / export ---------------------------------------------
    def events(self):
        """ts-sorted snapshot of every thread's ring buffer (the rings
        keep collecting; the snapshot is consistent per ring)."""
        with self._lock:
            merged = [ev for buf in self._buffers.values()
                      for ev in buf]
        merged.sort(key=lambda ev: ev.ts)
        return merged

    def clear(self):
        with self._lock:
            for buf in self._buffers.values():
                buf.clear()

    def thread_names(self):
        with self._lock:
            return dict(self._thread_names)

    def chrome_trace(self, process_name="paddle_tpu"):
        """The current buffers as a Catapult JSON dict (chrome://tracing
        / Perfetto `Open trace file` compatible)."""
        return to_chrome_trace(self.events(),
                               thread_names=self.thread_names(),
                               process_name=process_name)

    def dump(self, path, process_name="paddle_tpu"):
        """Write the current buffers as a chrome-trace JSON file;
        returns the path (the flight-recorder dump primitive)."""
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.chrome_trace(process_name), f)
        return path


def to_chrome_trace(events, thread_names=None, process_name=None,
                    pid=None):
    """Render ``TraceEvent``s (or pre-built event dicts) as a Catapult
    JSON dict: ``{"traceEvents": [...], "displayTimeUnit": "ms"}``.

    ``thread_names``/``process_name`` add the ``ph="M"`` metadata
    events chrome://tracing uses to label lanes; pass neither for a
    bare event list (utils/profiler.py's reference-parity export keeps
    exactly one JSON object per recorded span)."""
    pid = int(os.getpid() if pid is None else pid)
    out = []
    if process_name:
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "tid": 0, "args": {"name": str(process_name)}})
    for tid, tname in sorted((thread_names or {}).items()):
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": str(tname)}})
    for ev in events:
        out.append(ev.to_json(pid=pid) if isinstance(ev, TraceEvent)
                   else dict(ev))
    return {"traceEvents": out, "displayTimeUnit": "ms"}


_default_tracer = Tracer()


def default_tracer():
    """Process-wide default tracer (``RecordEvent("x")`` with no
    explicit tracer lands here) — the serving engine builds its OWN
    tracer per instance so two engines' ticks never interleave."""
    return _default_tracer
