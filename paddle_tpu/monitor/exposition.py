"""Prometheus text exposition (format 0.0.4) for the stat registry.

The reference had no exporter in-tree (SURVEY §5.5 — glog + pybind
stat getters only); serving needs scrapeable metrics, so this renders
every registered Counter/Gauge/Histogram/StatValue as the standard
``# HELP`` / ``# TYPE`` / sample-line triple that Prometheus,
VictoriaMetrics, and ``curl | grep`` all understand.
"""
from __future__ import annotations

from .stats import (Counter, Gauge, Histogram, StatValue,
                    default_registry, sanitize_name)


def _fmt(v):
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def _escape_help(text):
    """Prometheus text format 0.0.4: HELP text must escape ``\\`` as
    ``\\\\`` and line feeds as ``\\n`` — a raw newline would split the
    comment mid-line and corrupt the whole exposition (the line after
    it would parse as a malformed sample)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def render_prometheus(registry=None):
    """Render every metric in ``registry`` (default: the process-wide
    default registry) as Prometheus text exposition."""
    registry = registry or default_registry()
    lines = []
    # registry.items() is a sorted snapshot taken under one lock:
    # registrations landing mid-render (compile-event hooks, a sibling
    # engine initializing) never mutate the iteration — each metric's
    # own lock then keeps its sample lines internally consistent
    for name, m in registry.items():
        pname = sanitize_name(name)
        if m.help:
            lines.append(f"# HELP {pname} {_escape_help(m.help)}")
        if isinstance(m, Histogram):
            lines.append(f"# TYPE {pname} histogram")
            cum, total_sum, count = m.snapshot()
            for bound, c in zip(m.bounds, cum):
                lines.append(
                    f'{pname}_bucket{{le="{_fmt(bound)}"}} {c}')
            lines.append(f'{pname}_bucket{{le="+Inf"}} {count}')
            lines.append(f"{pname}_sum {_fmt(total_sum)}")
            lines.append(f"{pname}_count {count}")
        elif isinstance(m, Counter):
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname} {_fmt(m.value)}")
        elif isinstance(m, (Gauge, StatValue)):
            # StatValue maps onto gauge: it can decrease (STAT_SUB)
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {_fmt(m.value)}")
    return "\n".join(lines) + ("\n" if lines else "")
