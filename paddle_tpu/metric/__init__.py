"""Metrics (reference: python/paddle/metric/metrics.py — Metric base,
Accuracy, Precision, Recall, Auc; kernels operators/metrics/*)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        # jnp (not numpy) so this traces inside the compiled train step
        # (TrainStep computes prepared metrics in-graph; reference:
        # hapi/model.py:1495)
        import jax.numpy as jnp
        p = pred._data if isinstance(pred, Tensor) else jnp.asarray(pred)
        lab = label._data if isinstance(label, Tensor) else \
            jnp.asarray(label)
        order = jnp.argsort(-p, axis=-1)[..., :self.maxk]
        if lab.ndim == p.ndim:
            lab = lab.squeeze(-1)
        correct = (order == lab[..., None])
        return Tensor(correct.astype(jnp.float32))

    def update(self, correct, *args):
        arr = correct.numpy() if isinstance(correct, Tensor) else \
            np.asarray(correct)
        num = arr.shape[0] if arr.ndim else 1
        accs = []
        for k in self.topk:
            c = arr[..., :k].sum(-1).mean()
            accs.append(float(c))
        self.total[0] += float(arr[..., :self.maxk].any(-1).sum())
        self.count[0] += int(np.prod(arr.shape[:-1]))
        for i, k in enumerate(self.topk):
            self._correct_k[i] += float(arr[..., :k].sum())
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0]
        self.count = [0]
        self._correct_k = [0.0 for _ in self.topk]

    def accumulate(self):
        res = [ck / self.count[0] if self.count[0] else 0.0
               for ck in self._correct_k]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds.numpy() if isinstance(preds, Tensor)
                           else preds)
        labels = np.asarray(labels.numpy() if isinstance(labels, Tensor)
                            else labels).reshape(preds.shape)
        pred_pos = (preds > 0.5)
        self.tp += int(np.sum(pred_pos & (labels > 0.5)))
        self.fp += int(np.sum(pred_pos & (labels <= 0.5)))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds.numpy() if isinstance(preds, Tensor)
                           else preds)
        labels = np.asarray(labels.numpy() if isinstance(labels, Tensor)
                            else labels).reshape(preds.shape)
        pred_pos = (preds > 0.5)
        self.tp += int(np.sum(pred_pos & (labels > 0.5)))
        self.fn += int(np.sum(~pred_pos & (labels > 0.5)))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Threshold-bucketed AUC (reference: operators/metrics/auc_op.cc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds.numpy() if isinstance(preds, Tensor)
                           else preds)
        labels = np.asarray(labels.numpy() if isinstance(labels, Tensor)
                            else labels).reshape(-1)
        if preds.ndim == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        buckets = np.clip(
            (preds * self.num_thresholds).astype(np.int64), 0,
            self.num_thresholds)
        for b, l in zip(buckets, labels):
            if l > 0.5:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return auc / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """functional paddle.metric.accuracy"""
    pred = input.numpy() if isinstance(input, Tensor) else np.asarray(input)
    lab = label.numpy() if isinstance(label, Tensor) else np.asarray(label)
    order = np.argsort(-pred, axis=-1)[..., :k]
    if lab.ndim == pred.ndim:
        lab = lab.squeeze(-1)
    correct_any = (order == lab[..., None]).any(-1)
    return Tensor(np.asarray(correct_any.mean(), np.float32))

import sys as _sys
metrics = _sys.modules[__name__]  # reference: paddle.metric.metrics module alias
