"""paddle.static.nn — graph-building layer functions.

Reference parity: ``python/paddle/fluid/layers/nn.py`` (fc, conv2d,
batch_norm, embedding…) — the declarative twins of the nn.functional ops.
Each call creates eager Parameters (persistables) and applies the same
``primitive``-wrapped functionals, which record into the default Program
when handed symbolic Variables.  One op library serves both modes — the
reference needed per-op OpMaker+InferShape+kernels for this.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Parameter, Tensor
from ..nn import initializer as init_mod
from ..nn import functional as F
from ..utils import unique_name
from . import program as prog_mod


def _make_param(shape, dtype, attr, default_init, name_hint):
    name = None
    initializer = default_init
    if attr is not None and not isinstance(attr, bool):
        name = getattr(attr, "name", None)
        if getattr(attr, "initializer", None) is not None:
            initializer = attr.initializer
    return Parameter(initializer(shape, dtype),
                     name=name or unique_name.generate(name_hint))


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """reference: fluid/layers/nn.py fc — x @ W + b (+activation)."""
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    w = _make_param([in_dim, size], "float32", weight_attr,
                    init_mod.XavierUniform(), "fc_w")
    from .. import ops
    xf = ops.reshape(x, list(x.shape[:num_flatten_dims]) + [in_dim]) \
        if len(x.shape) > num_flatten_dims + 1 else x
    out = ops.matmul(xf, w)
    if bias_attr is not False:
        b = _make_param([size], "float32", bias_attr,
                        init_mod.Constant(0.0), "fc_b")
        out = out + b
    if activation:
        out = getattr(F, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    w = _make_param([num_filters, in_ch // groups] + list(filter_size),
                    "float32", param_attr, init_mod.XavierUniform(),
                    "conv_w")
    b = None
    if bias_attr is not False:
        b = _make_param([num_filters], "float32", bias_attr,
                        init_mod.Constant(0.0), "conv_b")
    out = F.conv2d(input, w, bias=b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups,
                   data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               moving_mean_name=None, moving_variance_name=None, name=None):
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    weight = _make_param([c], "float32", param_attr, init_mod.Constant(1.0),
                         "bn_scale")
    bias = _make_param([c], "float32", bias_attr, init_mod.Constant(0.0),
                       "bn_bias")
    mean = Tensor(np.zeros([c], "float32"),
                  name=moving_mean_name or unique_name.generate("bn_mean"))
    var = Tensor(np.ones([c], "float32"),
                 name=moving_variance_name or unique_name.generate("bn_var"))
    mean.persistable = var.persistable = True
    out = F.batch_norm(input, mean, var, weight, bias, training=not is_test,
                       momentum=momentum, epsilon=epsilon,
                       data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None):
    w = _make_param(list(size), dtype, param_attr, init_mod.Normal(0., .02),
                    "emb_w")
    return F.embedding(input, w, padding_idx=padding_idx, sparse=is_sparse)


def dropout(x, dropout_prob=0.5, is_test=False, seed=None, name=None):
    return F.dropout(x, p=dropout_prob, training=not is_test)


# control flow: symbolic cond/while over recorded subgraphs is intentionally
# NOT rebuilt (reference: operators/controlflow/conditional_block_op.cc,
# while_op.cc).  TPU-native control flow happens inside jitted fns with
# lax.cond/lax.while_loop via paddle.jit / dygraph-to-static.
