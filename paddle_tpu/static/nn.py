"""paddle.static.nn — graph-building layer functions.

Reference parity: ``python/paddle/fluid/layers/nn.py`` (fc, conv2d,
batch_norm, embedding…) — the declarative twins of the nn.functional ops.
Each call creates eager Parameters (persistables) and applies the same
``primitive``-wrapped functionals, which record into the default Program
when handed symbolic Variables.  One op library serves both modes — the
reference needed per-op OpMaker+InferShape+kernels for this.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Parameter, Tensor
from ..nn import initializer as init_mod
from ..nn import functional as F
from ..utils import unique_name
from . import program as prog_mod


def _make_param(shape, dtype, attr, default_init, name_hint):
    name = None
    initializer = default_init
    if attr is not None and not isinstance(attr, bool):
        name = getattr(attr, "name", None)
        if getattr(attr, "initializer", None) is not None:
            initializer = attr.initializer
    return Parameter(initializer(shape, dtype),
                     name=name or unique_name.generate(name_hint))


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """reference: fluid/layers/nn.py fc — x @ W + b (+activation)."""
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    w = _make_param([in_dim, size], "float32", weight_attr,
                    init_mod.XavierUniform(), "fc_w")
    from .. import ops
    xf = ops.reshape(x, list(x.shape[:num_flatten_dims]) + [in_dim]) \
        if len(x.shape) > num_flatten_dims + 1 else x
    out = ops.matmul(xf, w)
    if bias_attr is not False:
        b = _make_param([size], "float32", bias_attr,
                        init_mod.Constant(0.0), "fc_b")
        out = out + b
    if activation:
        out = getattr(F, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    w = _make_param([num_filters, in_ch // groups] + list(filter_size),
                    "float32", param_attr, init_mod.XavierUniform(),
                    "conv_w")
    b = None
    if bias_attr is not False:
        b = _make_param([num_filters], "float32", bias_attr,
                        init_mod.Constant(0.0), "conv_b")
    out = F.conv2d(input, w, bias=b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups,
                   data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               moving_mean_name=None, moving_variance_name=None, name=None):
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    weight = _make_param([c], "float32", param_attr, init_mod.Constant(1.0),
                         "bn_scale")
    bias = _make_param([c], "float32", bias_attr, init_mod.Constant(0.0),
                       "bn_bias")
    mean = Tensor(np.zeros([c], "float32"),
                  name=moving_mean_name or unique_name.generate("bn_mean"))
    var = Tensor(np.ones([c], "float32"),
                 name=moving_variance_name or unique_name.generate("bn_var"))
    mean.persistable = var.persistable = True
    out = F.batch_norm(input, mean, var, weight, bias, training=not is_test,
                       momentum=momentum, epsilon=epsilon,
                       data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32", name=None):
    w = _make_param(list(size), dtype, param_attr, init_mod.Normal(0., .02),
                    "emb_w")
    return F.embedding(input, w, padding_idx=padding_idx, sparse=is_sparse)


def dropout(x, dropout_prob=0.5, is_test=False, seed=None, name=None):
    return F.dropout(x, p=dropout_prob, training=not is_test)


# ---- control flow ---------------------------------------------------------
# reference: operators/controlflow/conditional_block_op.cc, while_op.cc,
# fluid/layers/control_flow.py cond/while_loop/case/switch_case.
# TPU-native: eager mode evaluates the Python predicate directly; under a
# jit trace the branches lower to lax.cond/lax.while_loop.  Inside a
# recorded Program, cond lifts each branch's recorded node span into a
# sub-graph and emits ONE fused lax.cond OpNode (_record_cond) — the
# conditional_block sub-block, without sub-block machinery.

def _unwrap_cf(x):
    from ..core.tensor import Tensor as _T
    return x._data if isinstance(x, _T) else x


def _wrap_cf(x):
    import jax
    from ..core.tensor import Tensor as _T
    if isinstance(x, (list, tuple)):
        return type(x)(_wrap_cf(v) for v in x)
    if hasattr(x, "dtype") and hasattr(x, "shape"):
        return _T(x)
    return x


def _record_cond(pred, true_fn, false_fn):
    """cond inside a recorded Program (round 5, closes VERDICT r4
    weak-#6): each branch is recorded into a throwaway node span, the
    span is lifted out as a sub-graph, and ONE fused OpNode executes
    both sub-graphs under ``lax.cond`` — the TPU-native analogue of the
    reference's conditional_block sub-block (conditional_block_op.cc)
    without sub-block machinery: XLA sees a single traced cond."""
    import jax
    from jax import lax
    from ..core.tensor import Tensor as _T
    from .program import Variable, OpNode, _flatten_result

    prog = pred.block.program
    if false_fn is None:
        raise ValueError(
            "static.nn.cond in a Program requires both branches "
            "(lax.cond needs matching output structures)")

    def record_branch(fn):
        n0 = len(prog.nodes)
        out = fn()
        sub = prog.nodes[n0:]
        del prog.nodes[n0:]
        for nd in sub:
            if not isinstance(nd, OpNode):
                raise NotImplementedError(
                    "static.nn.cond: branches may only record pure ops "
                    "(assign/backward inside a cond branch has no "
                    "single-block analogue)")
        is_leaf = lambda v: isinstance(v, (Variable, _T))
        leaves, treedef = jax.tree_util.tree_flatten(out,
                                                     is_leaf=is_leaf)
        internal = {vid for nd in sub for vid in nd.out_vids}
        return sub, leaves, treedef, internal

    sub_t, out_t, tree_t, int_t = record_branch(true_fn)
    sub_f, out_f, tree_f, int_f = record_branch(false_fn)
    if len(out_t) != len(out_f) or tree_t != tree_f:
        raise ValueError(
            f"static.nn.cond: branch return structures differ "
            f"({tree_t} vs {tree_f}) — lax.cond requires matching "
            "structures (reference: cond incompatible-return error)")

    # external refs either branch reads (or passes through): ordered,
    # deduped; ('v', vid) outer Variables and ('p', name) persistables
    ext_keys, ext_args = [], []

    def ext_of(kind, ref):
        key = (kind, ref)
        if key not in ext_keys:
            ext_keys.append(key)
            ext_args.append(prog.vars[ref] if kind == "v"
                            else prog.captures[ref])
        return ext_keys.index(key)

    for sub, internal in ((sub_t, int_t), (sub_f, int_f)):
        for nd in sub:
            for kind, ref in nd.in_refs:
                if kind == "p" or (kind == "v" and ref not in internal):
                    ext_of(kind, ref)

    def out_spec(leaves, internal):
        spec = []
        for lf in leaves:
            if isinstance(lf, Variable):
                if lf._vid in internal:
                    spec.append(("i", lf._vid))
                else:
                    spec.append(("e", ext_of("v", lf._vid)))
                continue
            # eager results (Tensor, or scalar/array constants) route
            # through a capture
            if not isinstance(lf, _T):
                try:
                    lf = _T(np.asarray(lf))
                except Exception:
                    raise TypeError(
                        "static.nn.cond: branches must return "
                        f"tensors/arrays, got {type(lf).__name__}")
            spec.append(("e", ext_of("p", prog.capture(lf))))
        return spec

    spec_t = out_spec(out_t, int_t)
    spec_f = out_spec(out_f, int_f)
    ext_index = {k: i for i, k in enumerate(ext_keys)}

    def make_runner(sub, spec):
        def run(ext_vals):
            env = {}

            def val(kind, ref):
                if kind == "c":
                    return ref
                if kind == "p" or (kind, ref) in ext_index:
                    return ext_vals[ext_index[(kind, ref)]]
                return env[ref]

            for nd in sub:
                args = [val(k, r) for k, r in nd.in_refs]
                res = nd.fn(*args, **nd.kwargs)
                for vid, leaf in zip(nd.out_vids,
                                     _flatten_result(res, nd.has_aux)):
                    env[vid] = leaf
            return tuple(env[r] if tag == "i" else ext_vals[r]
                         for tag, r in spec)
        return run

    run_t, run_f = make_runner(sub_t, spec_t), make_runner(sub_f, spec_f)

    import jax.numpy as jnp

    def fused(pred_val, *ext_vals):
        p = jnp.reshape(pred_val, ()).astype(bool)
        return lax.cond(p, run_t, run_f, tuple(ext_vals))

    res = prog.record_call("cond", fused, [pred] + ext_args, {})
    leaves = list(res) if isinstance(res, tuple) else [res]
    return jax.tree_util.tree_unflatten(tree_t, leaves)


def cond(pred, true_fn=None, false_fn=None, name=None,
         return_names=None):
    import jax
    p = _unwrap_cf(pred)
    if isinstance(p, jax.ShapeDtypeStruct):
        from .program import Variable
        if isinstance(pred, Variable):
            return _record_cond(pred, true_fn, false_fn)
        raise NotImplementedError(
            "static.nn.cond: abstract predicate outside a recorded "
            "Program — express the model with paddle.jit (XLA traces "
            "lax.cond natively)")
    if not isinstance(p, jax.core.Tracer):
        return true_fn() if bool(p) else (
            false_fn() if false_fn is not None else None)
    if false_fn is None:
        raise ValueError(
            "cond under jit requires both branches (lax.cond needs "
            "matching output structures); pass a false_fn")

    def _branch(fn):
        def run(_):
            out = fn()
            return jax.tree_util.tree_map(
                _unwrap_cf, out,
                is_leaf=lambda v: hasattr(v, "_data"))
        return run

    out = jax.lax.cond(p, _branch(true_fn), _branch(false_fn), 0)
    return _wrap_cf(out)


def _record_while(cond_fn, body_fn, loop_vars, prog=None):
    """while_loop inside a recorded Program (round 5, same sub-graph
    lift as ``_record_cond``): the condition and body node spans become
    one fused OpNode running ``lax.while_loop`` with the loop vars as
    carry (reference: while_op.cc's sub-block, without sub-blocks)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from ..core.tensor import Tensor as _T
    from .program import Variable, OpNode, _flatten_result

    if prog is None:
        prog = next(v for v in loop_vars
                    if isinstance(v, Variable)).block.program
    # loop vars must be SYMBOLIC while the spans record — an eager
    # Tensor loop var would evaluate body ops eagerly (to constants)
    # and the carry would never feed back (the r5 hang).  Eager loop
    # vars get stand-in Variables for recording; their original
    # Tensors supply the initial carry values through record_call.
    sym_vars, loop_keys = [], []
    for v in loop_vars:
        if isinstance(v, Variable):
            sym_vars.append(v)
            loop_keys.append(("v", v._vid))
        elif isinstance(v, _T):
            sv = Variable(prog.global_block(), v.shape, v.dtype,
                          name=unique_name.generate("while_carry"))
            sym_vars.append(sv)
            loop_keys.append(("v", sv._vid))
        else:
            raise TypeError(
                "static.nn.while_loop in a Program: loop_vars must be "
                f"Variables/Tensors, got {type(v)}")
    loop_pos = {k: i for i, k in enumerate(loop_keys)}

    def record_span(fn):
        n0 = len(prog.nodes)
        out = fn(*sym_vars)
        sub = prog.nodes[n0:]
        del prog.nodes[n0:]
        for nd in sub:
            if not isinstance(nd, OpNode):
                raise NotImplementedError(
                    "static.nn.while_loop: loop bodies may only record "
                    "pure ops in a Program")
        internal = {vid for nd in sub for vid in nd.out_vids}
        return sub, out, internal

    sub_c, out_c, int_c = record_span(cond_fn)
    sub_b, out_b, int_b = record_span(body_fn)
    out_b = list(out_b) if isinstance(out_b, (list, tuple)) \
        else [out_b]
    if len(out_b) != len(loop_vars):
        raise ValueError(
            f"static.nn.while_loop: body returns {len(out_b)} values "
            f"for {len(loop_vars)} loop vars")

    ext_keys, ext_args = [], []

    def ext_of(kind, ref):
        key = (kind, ref)
        if key in loop_pos:
            return None
        if key not in ext_keys:
            ext_keys.append(key)
            ext_args.append(prog.vars[ref] if kind == "v"
                            else prog.captures[ref])
        return ext_keys.index(key)

    for sub, internal in ((sub_c, int_c), (sub_b, int_b)):
        for nd in sub:
            for kind, ref in nd.in_refs:
                if kind == "c" or (kind == "v" and ref in internal):
                    continue
                ext_of(kind, ref)
    ext_index = {k: i for i, k in enumerate(ext_keys)}

    def spec_of(leaf, internal):
        if isinstance(leaf, Variable):
            key = ("v", leaf._vid)
            if leaf._vid in internal:
                return ("i", leaf._vid)
        else:
            key = ("p", prog.capture(leaf))
        if key in loop_pos:
            return ("l", loop_pos[key])
        return ("e", ext_of(*key))

    body_spec = [spec_of(lf, int_b) for lf in out_b]
    cond_spec = spec_of(out_c, int_c)

    def make_runner(sub, internal):
        def run(carry, ext_vals):
            env = {}

            def val(kind, ref):
                if kind == "c":
                    return ref
                key = (kind, ref)
                if key in loop_pos:
                    return carry[loop_pos[key]]
                if kind == "v" and ref in internal:
                    return env[ref]
                return ext_vals[ext_index[key]]

            for nd in sub:
                args = [val(k, r) for k, r in nd.in_refs]
                res = nd.fn(*args, **nd.kwargs)
                for vid, leaf in zip(nd.out_vids,
                                     _flatten_result(res,
                                                     nd.has_aux)):
                    env[vid] = leaf
            return env
        return run

    run_c = make_runner(sub_c, int_c)
    run_b = make_runner(sub_b, int_b)

    def resolve(spec, env, carry, ext_vals):
        tag, r = spec
        if tag == "i":
            return env[r]
        if tag == "l":
            return carry[r]
        return ext_vals[r]

    n_loop = len(loop_vars)

    def fused(*vals):
        carry0 = tuple(vals[:n_loop])
        ext_vals = tuple(vals[n_loop:])

        def c(carry):
            env = run_c(carry, ext_vals)
            p = resolve(cond_spec, env, carry, ext_vals)
            return jnp.reshape(p, ()).astype(bool)

        def b(carry):
            env = run_b(carry, ext_vals)
            return tuple(resolve(s, env, carry, ext_vals)
                         for s in body_spec)

        return lax.while_loop(c, b, carry0)

    res = prog.record_call("while_loop", fused,
                           list(loop_vars) + ext_args, {})
    return list(res) if isinstance(res, tuple) else [res]


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    import jax
    from .program import Variable
    if any(isinstance(v, Variable) for v in loop_vars):
        return _record_while(cond_fn, body_fn, loop_vars)
    if prog_mod.in_static_mode():
        # loop_vars may all be eager (creation ops evaluate eagerly in
        # static mode) while the condition/body still touch recorded
        # Variables through their closures — probe the condition once,
        # roll the probe's nodes back, and record for real if symbolic
        prog = prog_mod.default_main_program()
        n0 = len(prog.nodes)
        probe = cond_fn(*loop_vars)
        del prog.nodes[n0:]
        if isinstance(probe, Variable):
            return _record_while(cond_fn, body_fn, loop_vars,
                                 prog=prog)
    arrs = [_unwrap_cf(v) for v in loop_vars]
    traced = any(isinstance(a, jax.core.Tracer) for a in arrs)
    if not traced:
        vals = list(loop_vars)
        while bool(_unwrap_cf(cond_fn(*vals))):
            out = body_fn(*vals)
            vals = list(out) if isinstance(out, (list, tuple)) else [out]
        return vals

    def c(vs):
        return _unwrap_cf(cond_fn(*_wrap_cf(list(vs))))

    def b(vs):
        out = body_fn(*_wrap_cf(list(vs)))
        out = out if isinstance(out, (list, tuple)) else [out]
        return tuple(_unwrap_cf(o) for o in out)

    res = jax.lax.while_loop(c, b, tuple(arrs))
    return _wrap_cf(list(res))


def case(pred_fn_pairs, default=None, name=None):
    import jax
    from .program import Variable
    for i, (pred, fn) in enumerate(pred_fn_pairs):
        p = _unwrap_cf(pred)
        if isinstance(pred, Variable) or isinstance(p, jax.core.Tracer):
            # symbolic predicate (recorded Program or jit trace):
            # chain through cond, which handles both regimes
            rest = pred_fn_pairs[i + 1:]
            if rest:
                nxt = lambda: case(rest, default)  # noqa: E731
            elif default is not None:
                nxt = default
            else:
                raise ValueError(
                    "case with a symbolic predicate requires a default "
                    "branch (lax.cond needs an else)")
            return cond(pred, fn, nxt)
        if bool(p):
            return fn()
    return default() if default is not None else None


def switch_case(branch_index, branch_fns, default=None, name=None):
    import jax
    import jax.numpy as _jnp
    idx = _unwrap_cf(branch_index)
    fns = dict(branch_fns) if not isinstance(branch_fns, dict) \
        else branch_fns
    keys = sorted(fns)
    if default is None:
        # reference semantics (fluid/layers/control_flow.py switch_case):
        # without a default, the LAST branch serves as the default
        default = fns[keys[-1]]
    from .program import Variable
    if isinstance(branch_index, Variable):
        # record-mode Program: equality-chained record-capable conds.
        # When the default was auto-filled from the LAST branch, skip
        # that branch's own equality test — it would record the same
        # subgraph twice as both arms of the final cond
        chain_keys = keys[:-1] if default is fns[keys[-1]] else keys
        pairs = [(branch_index == k, fns[k]) for k in chain_keys]
        return case(pairs, default)
    if not isinstance(idx, jax.core.Tracer):
        return fns.get(int(idx), default)()
    branches = [lambda _, f=fns[k]: jax.tree_util.tree_map(
        _unwrap_cf, f(), is_leaf=lambda v: hasattr(v, "_data"))
        for k in keys]
    branches.append(lambda _: jax.tree_util.tree_map(
        _unwrap_cf, default(), is_leaf=lambda v: hasattr(v, "_data")))
    # exact-match dispatch: any non-member index takes the default branch
    matches = _jnp.asarray(keys) == idx
    pos = _jnp.where(_jnp.any(matches), _jnp.argmax(matches),
                     len(branches) - 1)
    out = jax.lax.switch(pos, branches, 0)
    return _wrap_cf(out)


# ---- remaining static.nn graph builders (reference static/nn/__init__) ----

def _graph_norm(norm_layer_cls, input, *cls_args, act=None, **cls_kwargs):
    layer = norm_layer_cls(*cls_args, **cls_kwargs)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    from ..nn import LayerNorm
    return _graph_norm(
        LayerNorm, input, input.shape[begin_norm_axis:], act=act,
        epsilon=epsilon,
        weight_attr=(param_attr if scale else False),
        bias_attr=(bias_attr if shift else False))


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    if data_layout != "NCHW":
        raise NotImplementedError(
            "static.nn.group_norm: only NCHW is supported (channel-last "
            "normalization would silently use the wrong axis)")
    from ..nn import GroupNorm
    return _graph_norm(GroupNorm, input, groups, input.shape[1], act=act,
                       epsilon=epsilon)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from ..nn import InstanceNorm2D
    return _graph_norm(InstanceNorm2D, input, input.shape[1],
                       epsilon=epsilon)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from ..nn import SpectralNorm
    return SpectralNorm(weight.shape, axis=dim,
                        power_iters=power_iters, epsilon=eps)(weight)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None, name=None,
              **kwargs):
    """reference data_norm_op: normalization by ACCUMULATED stats (never
    the current minibatch) — served by batch_norm in global-stats mode;
    the reference's online accumulation of batch_sum/batch_square_sum is
    not reproduced."""
    return batch_norm(input, act=act, epsilon=epsilon,
                      param_attr=param_attr, is_test=True)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None,
                     data_format="NCHW", name=None):
    if filter_size is None:
        raise ValueError(
            "conv2d_transpose: filter_size is required (deriving it from "
            "output_size is not supported — pass the kernel explicitly)")
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    w = _make_param([in_ch, num_filters // groups] + list(filter_size),
                    "float32", param_attr, init_mod.XavierUniform(),
                    "convT_w")
    b = None
    if bias_attr is not False:
        b = _make_param([num_filters], "float32", bias_attr,
                        init_mod.Constant(0.0), "convT_b")
    out = F.conv2d_transpose(input, w, bias=b, stride=stride,
                             padding=padding, dilation=dilation,
                             groups=groups, data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, data_format="NCDHW", name=None):
    if data_format != "NCDHW":
        raise NotImplementedError(
            "static.nn.conv3d: only NCDHW is supported")
    if isinstance(filter_size, int):
        filter_size = [filter_size] * 3
    in_ch = input.shape[1]
    w = _make_param([num_filters, in_ch // groups] + list(filter_size),
                    "float32", param_attr, init_mod.XavierUniform(),
                    "conv3d_w")
    b = None
    if bias_attr is not False:
        b = _make_param([num_filters], "float32", bias_attr,
                        init_mod.Constant(0.0), "conv3d_b")
    out = F.conv3d(input, w, bias=b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups)
    if act:
        out = getattr(F, act)(out)
    return out


def conv3d_transpose(input, num_filters, output_size=None,
                     filter_size=None, stride=1, padding=0, dilation=1,
                     groups=1, param_attr=None, bias_attr=None, act=None,
                     data_format="NCDHW", name=None):
    if data_format != "NCDHW":
        raise NotImplementedError(
            "static.nn.conv3d_transpose: only NCDHW is supported")
    if filter_size is None:
        raise ValueError(
            "conv3d_transpose: filter_size is required (deriving it from "
            "output_size is not supported — pass the kernel explicitly)")
    if isinstance(filter_size, int):
        filter_size = [filter_size] * 3
    in_ch = input.shape[1]
    w = _make_param([in_ch, num_filters // groups] + list(filter_size),
                    "float32", param_attr, init_mod.XavierUniform(),
                    "conv3dT_w")
    b = None
    if bias_attr is not False:
        b = _make_param([num_filters], "float32", bias_attr,
                        init_mod.Constant(0.0), "conv3dT_b")
    out = F.conv3d_transpose(input, w, bias=b, stride=stride,
                             padding=padding, dilation=dilation,
                             groups=groups)
    if act:
        out = getattr(F, act)(out)
    return out


def prelu(x, mode="all", param_attr=None, name=None):
    if mode == "element":
        # per-element alpha broadcasts over batch only; F.prelu's 1-D
        # channel reshape does not apply here
        alpha = _make_param([1] + list(x.shape[1:]), "float32",
                            param_attr, init_mod.Constant(0.25),
                            "prelu_alpha")
        from .. import ops
        zero = 0.0
        return ops.maximum(x, zero) + alpha * ops.minimum(x, zero)
    n_alpha = 1 if mode == "all" else x.shape[1]
    alpha = _make_param([n_alpha], "float32", param_attr,
                        init_mod.Constant(0.25), "prelu_alpha")
    return F.prelu(x, alpha)


def bilinear_tensor_product(x, y, size, act=None, param_attr=None,
                            bias_attr=None, name=None):
    w = _make_param([size, x.shape[-1], y.shape[-1]], "float32",
                    param_attr, init_mod.XavierUniform(), "bilinear_w")
    b = None
    if bias_attr is not False:
        b = _make_param([size], "float32", bias_attr,
                        init_mod.Constant(0.0), "bilinear_b")
    out = F.bilinear(x, y, w, b)
    if act:
        out = getattr(F, act)(out)
    return out


def deform_conv2d(input, offset, mask=None, num_filters=1, filter_size=3,
                  stride=1, padding=0, dilation=1, groups=1,
                  deformable_groups=1, param_attr=None, bias_attr=None,
                  name=None):
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    in_ch = input.shape[1]
    w = _make_param([num_filters, in_ch // groups] + list(filter_size),
                    "float32", param_attr, init_mod.XavierUniform(),
                    "dcn_w")
    b = None
    if bias_attr is not False:
        b = _make_param([num_filters], "float32", bias_attr,
                        init_mod.Constant(0.0), "dcn_b")
    from ..vision.ops import deform_conv2d as _dcn
    return _dcn(input, offset, w, bias=b, stride=stride, padding=padding,
                dilation=dilation, deformable_groups=deformable_groups,
                groups=groups, mask=mask)


def crf_decoding(input, param_attr=None, length=None, label=None,
                 name=None, transition=None):
    """reference crf_decoding_op — viterbi over a trained transition.
    Works on eager tensors AND symbolic Variables (the viterbi primitive
    records like any other op)."""
    if transition is None:
        raise ValueError(
            "crf_decoding: pass transition= (the linear_chain_crf "
            "parameter); the reference reads it from param_attr's scope "
            "entry, which has no analogue here")
    import numpy as _np
    n = int(input.shape[-1])
    tr = transition.numpy() if hasattr(transition, "numpy") else \
        _np.asarray(transition)
    # fluid [n+2, n] CRF layout -> the square layout _viterbi expects
    sq = _np.full((n + 2, n + 2), -1e9, _np.float32)
    sq[:n, :n] = tr[2:]
    sq[n, :n] = tr[0]
    sq[:n, n + 1] = tr[1]
    from ..core.tensor import Tensor as _T
    from .. import ops
    pad = _T(_np.full(tuple(input.shape[:-1]) + (2,), -1e9, _np.float32))
    em_pad = ops.concat([input, pad], axis=-1)
    if length is None:
        length = _np.full((int(input.shape[0]),), int(input.shape[1]),
                          _np.int32)
    length = length if isinstance(length, Tensor) else _T(
        _np.asarray(length))
    from ..nn.functional.extension import viterbi_decode
    _, path = viterbi_decode(em_pad, _T(sq), length)
    return path


def sparse_embedding(input, size, padding_idx=None, param_attr=None,
                     dtype="float32", name=None, is_test=False,
                     entry=None):
    """reference: PS distributed_lookup_table path → mesh-sharded table
    (distributed/ps.py) for the huge-vocab case; plain embedding here."""
    return embedding(input, size, is_sparse=True,
                     padding_idx=padding_idx, param_attr=param_attr,
                     dtype=dtype)


def row_conv(input, future_context_size, param_attr=None, act=None):
    w = _make_param([future_context_size + 1, input.shape[-1]],
                    "float32", param_attr, init_mod.XavierUniform(),
                    "row_conv_w")
    from ..nn.functional.sequence import row_conv as _rc
    out = _rc(input, w)
    if act:
        out = getattr(F, act)(out)
    return out


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    from ..nn import NCELoss
    layer = NCELoss(input.shape[-1], num_total_classes,
                    num_neg_samples=num_neg_samples, sampler=sampler)
    return layer(input, label)


def multi_box_head(inputs, image, base_size, num_classes,
                   aspect_ratios, min_ratio=None, max_ratio=None,
                   min_sizes=None, max_sizes=None, steps=None,
                   step_w=None, step_h=None, offset=0.5,
                   variance=(0.1, 0.1, 0.2, 0.2), flip=True, clip=False,
                   kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head (reference: fluid/layers/detection.py
    multi_box_head): per-feature-map prior boxes + 1x1/3x3 conv heads
    for location and confidence, flattened and concatenated.

    Returns (mbox_locs [N, num_priors, 4],
             mbox_confs [N, num_priors, num_classes],
             boxes [num_priors, 4], variances [num_priors, 4]).
    """
    from ..vision.ops import prior_box as _prior_box
    from ..ops.manipulation import concat, reshape, transpose

    inputs = list(inputs)
    n_in = len(inputs)
    if min_sizes is None:
        # the reference's min_ratio/max_ratio ladder (percent units):
        # first map uses base_size*10%/20%; the rest interpolate
        if min_ratio is None or max_ratio is None:
            raise ValueError(
                "multi_box_head: pass min_sizes/max_sizes or "
                "min_ratio/max_ratio (reference detection.py:2093)")
        min_sizes, max_sizes = [], []
        if n_in > 2:
            ratio_step = int((max_ratio - min_ratio) / (n_in - 2))
            for r in range(int(min_ratio), int(max_ratio) + 1,
                           ratio_step):
                min_sizes.append(base_size * r / 100.0)
                max_sizes.append(base_size * (r + ratio_step) / 100.0)
        elif n_in == 2:
            # the reference ladder divides by (n_in - 2) and would
            # crash here; give the second map the full min..max ratio
            # span instead — warn so ported 2-map SSD configs know
            # their prior sizes deliberately differ
            import warnings
            warnings.warn(
                "multi_box_head: 2 input maps with min_ratio/max_ratio "
                "— the reference's ratio ladder divides by zero here; "
                "the second map gets the full min..max span (prior "
                "sizes differ from any reference run)", UserWarning)
            min_sizes.append(base_size * min_ratio / 100.0)
            max_sizes.append(base_size * max_ratio / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes
        max_sizes = [base_size * 0.20] + max_sizes

    locs, confs, all_boxes, all_vars = [], [], [], []
    for i, inp in enumerate(inputs):
        ms = min_sizes[i]
        ms = ms if isinstance(ms, (list, tuple)) else [ms]
        mx = None
        if max_sizes:
            mx = max_sizes[i]
            mx = mx if isinstance(mx, (list, tuple)) else [mx]
        ar = aspect_ratios[i]
        ar = ar if isinstance(ar, (list, tuple)) else [ar]
        st = None
        if steps:
            st = steps[i] if isinstance(steps[i], (list, tuple)) \
                else [steps[i], steps[i]]
        elif step_w or step_h:
            st = [step_w[i] if step_w else 0.0,
                  step_h[i] if step_h else 0.0]
        boxes, vars_ = _prior_box(
            inp, image, ms, mx, ar, variance, flip, clip,
            steps=st or (0.0, 0.0), offset=offset,
            min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        num_priors_per_loc = boxes.shape[2]
        all_boxes.append(reshape(boxes, [-1, 4]))
        all_vars.append(reshape(vars_, [-1, 4]))

        # conv heads predict P*4 locs and P*C scores per location
        loc = conv2d(inp, num_priors_per_loc * 4, kernel_size,
                     stride=stride, padding=pad)
        loc = transpose(loc, [0, 2, 3, 1])           # NCHW -> NHWC
        locs.append(reshape(loc, [loc.shape[0], -1, 4]))
        conf = conv2d(inp, num_priors_per_loc * num_classes,
                      kernel_size, stride=stride, padding=pad)
        conf = transpose(conf, [0, 2, 3, 1])
        confs.append(reshape(conf, [conf.shape[0], -1, num_classes]))

    mbox_locs = concat(locs, axis=1)
    mbox_confs = concat(confs, axis=1)
    boxes = concat(all_boxes, axis=0)
    variances = concat(all_vars, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    """Reference: fluid/layers/nn.py py_func + operators/py_func_op.cc."""
    from ..ops.py_func import py_func as _impl
    return _impl(func, x, out, backward_func=backward_func,
                 skip_vars_in_backward_input=skip_vars_in_backward_input)


from ..ops.compat_ops import create_parameter  # noqa: E402,F401
