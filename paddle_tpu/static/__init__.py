"""paddle.static — declarative graph mode, TPU-native.

Reference parity: the static-graph half of the reference (``fluid/
framework.py`` Program/Block/Variable, ``fluid/executor.py``,
``fluid/backward.py``, ``fluid/layers/nn.py``).  See program.py /
executor.py docstrings for the design mapping (deferred op graph → one
jax.jit'd function instead of ProgramDesc → op-by-op interpreter).
"""
from __future__ import annotations

from ..core import dtype as dtypes
from ..core import dispatch as _dispatch

from .program import (Program, Variable, Block, enable_static,  # noqa: F401
                      disable_static, in_static_mode, in_dynamic_mode,
                      default_main_program, default_startup_program,
                      program_guard, data, global_scope, scope_guard,
                      Scope, append_backward, append_optimize,
                      _record_hook)
from .executor import Executor, save, load  # noqa: F401
from .io import (save_inference_model, load_inference_model,  # noqa: F401
                 InferenceProgram)
from . import io  # noqa: F401
from . import nn  # noqa: F401
from .compat import (  # noqa: F401
    BuildStrategy, ExecutionStrategy, CompiledProgram, ParallelExecutor,
    cpu_places, cuda_places, xpu_places, device_guard,
    WeightNormParamAttr, accuracy, auc, Print,
    serialize_program, deserialize_program, serialize_persistables,
    deserialize_persistables, save_to_file, load_from_file,
    load_program_state, set_program_state, save_vars, load_vars)
from . import amp  # noqa: F401
from ..ops.compat_ops import (  # noqa: F401
    create_global_var, create_parameter)

# NOTE: the op-dispatch recorder hook is installed by enable_static() and
# removed by disable_static(), so dynamic mode pays no dispatch overhead.


class InputSpec:
    """paddle.static.InputSpec — shape/dtype declaration for jit.save."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = dtypes.canonical_name(dtype)
        self.name = name

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name)


class name_scope:
    def __init__(self, prefix=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


from .nn import py_func  # noqa: E402,F401  (reference: fluid/layers/nn.py)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference fluid/backward.py gradients() — static grad query.

    Multiple targets are summed (matching the reference's accumulation of
    grad contributions across targets)."""
    if target_gradients is not None:
        raise NotImplementedError(
            "static.gradients: target_gradients (custom output cotangents) "
            "is not supported yet")
    if no_grad_set:
        raise NotImplementedError(
            "static.gradients: no_grad_set is not supported yet; pass only "
            "the wanted inputs instead")
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    total = targets[0]
    for t in targets[1:]:
        total = total + t
    pairs = append_backward(total, parameter_list=list(inputs))
    return [g for _, g in pairs]
