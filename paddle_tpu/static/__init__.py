"""paddle.static parity shims.

The reference's static graph (ProgramDesc + Executor) has no TPU analogue —
SURVEY.md §7 layer 4: the trace-compile boundary IS the static mode.  This
module keeps the handful of static-API entry points that user code touches
(InputSpec, default programs as opaque handles, name scopes).
"""
from __future__ import annotations

from ..core import dtype as dtypes


class InputSpec:
    """paddle.static.InputSpec — shape/dtype declaration for jit.save."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = list(shape)
        self.dtype = dtypes.canonical_name(dtype)
        self.name = name

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name)


class Program:
    """Opaque placeholder: XLA owns the compiled program."""

    def __init__(self):
        self._is_start_up = False

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


class program_guard:
    def __init__(self, main_program=None, startup_program=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class name_scope:
    def __init__(self, prefix=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    raise NotImplementedError(
        "py_func: host callbacks map to jax.pure_callback; not yet wired")
