"""paddle.static.amp (reference: contrib/mixed_precision/decorator.py).

On TPU the static executor computes in the declared dtypes and bf16 needs
no loss scaling, so ``decorate`` records the config and returns an
optimizer whose ``amp_init`` casts eligible persistables to bf16 when
``use_bf16``/pure-fp16 mode is requested.  The white/black lists mirror
``contrib/mixed_precision/fp16_lists.py``.
"""
from __future__ import annotations

import numpy as np

white_list = {"conv2d", "matmul", "matmul_v2", "mul"}
black_list = {"exp", "square", "log", "mean", "sum", "softmax",
              "softmax_with_cross_entropy", "cross_entropy"}


class CustomOpLists:
    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(white_list) | set(custom_white_list or ())
        self.black_list = set(black_list) | set(custom_black_list or ())


AutoMixedPrecisionLists = CustomOpLists


class OptimizerWithMixedPrecision:
    def __init__(self, optimizer, amp_lists=None, init_loss_scaling=2.**15,
                 use_dynamic_loss_scaling=True, use_pure_fp16=False,
                 use_bf16=True, **kwargs):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or CustomOpLists()
        self._use_pure_fp16 = use_pure_fp16
        self._use_bf16 = use_bf16
        self._loss_scaling = init_loss_scaling

    def __getattr__(self, name):
        return getattr(self._optimizer, name)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self._optimizer.minimize(loss, startup_program,
                                        parameter_list, no_grad_set)

    def amp_init(self, place=None, scope=None, test_program=None,
                 use_fp16_test=False):
        """Cast matmul/conv persistables to bf16 for pure low-precision
        runs (reference: decorator.py amp_init casting to fp16)."""
        if not (self._use_pure_fp16 and self._use_bf16):
            return
        from . import program as prog_mod
        import jax.numpy as jnp
        prog = prog_mod.default_main_program()
        for name, t in prog.captures.items():
            if t.trainable and t._data.ndim >= 2:
                t._data = t._data.astype(jnp.bfloat16)


def decorate(optimizer, amp_lists=None, init_loss_scaling=2.**15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True, use_pure_fp16=False,
             use_fp16_guard=None, use_bf16=True):
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling,
        use_dynamic_loss_scaling, use_pure_fp16, use_bf16)
