"""Static graph: Program / Block / Variable and the op recorder.

Reference parity: the declarative ("static graph") mode —
``python/paddle/fluid/framework.py`` Program (:4127) / Block (:2641) /
Operator (:2042) / Variable (:978), built by the same layer code that runs
eagerly, then executed by an Executor.

TPU-native design: a Program is NOT a serialized ProgramDesc interpreted op
by op (the reference's ``framework.proto`` + ``executor.cc:166`` path).  It
is a deferred op graph: every ``core.dispatch.primitive`` call whose inputs
contain a symbolic :class:`Variable` appends an :class:`OpNode` (the pure
jax function + argument bindings) instead of executing.  Shape inference is
``jax.eval_shape`` over the recorded function — the exact analogue of the
reference's compile-time InferShape (``framework/shape_inference.h``).  The
Executor then composes the node list into one Python function and hands it
to ``jax.jit``: XLA plays the role of ParallelExecutor + all 142 IR passes.

Parameters created while building (eager Tensors) are captured as named
*persistables* — the analogue of scope-resident variables
(``framework/scope.h:52``); optimizer updates write back into them.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core import dtype as dtypes
from ..utils import unique_name

# ---------------------------------------------------------------------------
# mode switch (paddle.enable_static / paddle.disable_static)

_static_mode = [False]


def enable_static():
    _static_mode[0] = True
    # install the recorder only while static mode is on so dynamic-mode op
    # dispatch pays zero overhead (mirrors amp_input_hook gating)
    from ..core import dispatch as _dispatch
    _dispatch.static_record_hook = _record_hook


def disable_static():
    _static_mode[0] = False
    from ..core import dispatch as _dispatch
    _dispatch.static_record_hook = None


def in_static_mode():
    return _static_mode[0]


def in_dynamic_mode():
    return not _static_mode[0]


# ---------------------------------------------------------------------------


class Variable(Tensor):
    """Symbolic tensor inside a Program (reference: framework.py:978).

    ``_data`` is a ``jax.ShapeDtypeStruct`` so shape/dtype propagate through
    the same Tensor-facing code paths that eager arrays use.
    """

    def __init__(self, block, shape, dtype, name=None):
        # deliberately does NOT call Tensor.__init__ (no concrete storage)
        self._data = jax.ShapeDtypeStruct(
            tuple(int(s) for s in shape), dtypes.to_jax(dtype))
        self._stop_gradient = True
        self.grad = None
        self._grad_node = None
        self._retain_grad = False
        self.name = name or unique_name.generate("var")
        self.persistable = False
        self.block = block
        self._vid = block.program._new_vid(self)

    def numpy(self):
        raise RuntimeError(
            f"Variable '{self.name}' is symbolic (static graph mode); run it "
            "through Executor.run(fetch_list=[...]) to get a value. "
            "(reference parity: fluid Variables have no data until run)")

    def __bool__(self):
        # the object default (always True) turns `while cond(...)` over
        # a symbolic Variable into a silent infinite recording loop
        raise TypeError(
            f"Variable '{self.name}' is symbolic — its truth value is "
            "unknown at graph-build time; use static.nn.cond/while_loop "
            "for data-dependent control flow")

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype})")

    __str__ = __repr__


class OpNode:
    """One recorded op: pure jax fn + bindings (reference: Operator)."""

    __slots__ = ("op_name", "fn", "kwargs", "in_refs", "out_vids", "has_aux")

    def __init__(self, op_name, fn, kwargs, in_refs, out_vids, has_aux):
        self.op_name = op_name
        self.fn = fn
        self.kwargs = kwargs
        self.in_refs = in_refs    # list of ('v', vid) | ('p', name) | ('c', x)
        self.out_vids = out_vids
        self.has_aux = has_aux


class AssignNode:
    """Write a graph value back into a persistable (e.g. BN moving stats)."""

    __slots__ = ("capture_name", "vid")

    def __init__(self, capture_name, vid):
        self.capture_name = capture_name
        self.vid = vid


class BackwardNode:
    """append_backward marker (reference: fluid/backward.py:1337).

    At execution the composed forward up to ``loss_vid`` runs under
    ``jax.value_and_grad`` w.r.t. the listed persistable parameters and/or
    symbolic Variables — the TPU-native replacement for per-op grad-op
    descs.
    """

    __slots__ = ("loss_vid", "param_names", "grad_vids", "var_vids")

    def __init__(self, loss_vid, param_names, grad_vids, var_vids=None):
        self.loss_vid = loss_vid
        self.param_names = param_names       # capture names (trainable)
        self.grad_vids = grad_vids           # {param_name: vid of X@GRAD}
        self.var_vids = var_vids or {}       # {input vid: vid of X@GRAD}


class OptimizeNode:
    """Optimizer update over (param, grad) pairs + persistable opt state."""

    __slots__ = ("optimizer", "entries")

    def __init__(self, optimizer, entries):
        # entries: list of (param_name, grad_vid, {slot: state_capture_name})
        self.optimizer = optimizer
        self.entries = entries


class Block:
    """reference framework.py:2641 — flat op list (single block per program;
    control flow maps to lax.cond/scan inside recorded fns, not sub-blocks).
    """

    def __init__(self, program, idx=0):
        self.program = program
        self.idx = idx
        self.ops = program.nodes

    def var(self, name):
        return self.program.var(name)

    def all_parameters(self):
        return [t for t in self.program.captures.values()
                if t.persistable and t.trainable]


class Program:
    """reference framework.py:4127."""

    def __init__(self):
        self.nodes = []
        self.vars = {}           # vid -> Variable
        self.captures = {}       # name -> eager Tensor (persistable)
        self._capture_by_id = {} # id(tensor) -> name
        self.feed_vars = {}      # name -> Variable
        self.rng_vids = []       # vids fed a fresh PRNG key every run
        self.version = 0
        self._next_vid = [0]
        self.blocks = [Block(self)]
        self.random_seed = None

    # -- structure ---------------------------------------------------------
    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        return self.blocks[0]

    def block(self, i):
        return self.blocks[i]

    @property
    def num_blocks(self):
        return len(self.blocks)

    def all_parameters(self):
        return self.global_block().all_parameters()

    def list_vars(self):
        return list(self.vars.values())

    def var(self, name):
        for v in self.vars.values():
            if v.name == name:
                return v
        if name in self.captures:
            return self.captures[name]
        raise KeyError(f"no variable named {name!r} in program")

    def clone(self, for_test=False):
        # The graph is pure w.r.t. the recorded fns; test-mode differences
        # (dropout off, BN eval) must be built under a test-mode guard the
        # way the reference rebuilds with is_test=True.
        return self

    # -- recording ---------------------------------------------------------
    def _new_vid(self, var):
        vid = self._next_vid[0]
        self._next_vid[0] += 1
        self.vars[vid] = var
        return vid

    def capture(self, tensor):
        """Register an eager Tensor as a named persistable input."""
        key = id(tensor)
        if key in self._capture_by_id:
            return self._capture_by_id[key]
        name = tensor.name or unique_name.generate("persist")
        while name in self.captures:
            name = unique_name.generate(name)
        self.captures[name] = tensor
        self._capture_by_id[key] = name
        return name

    def record_call(self, op_name, fn, args, kwargs, has_aux=False):
        in_refs, abstract = [], []
        for a in args:
            if isinstance(a, Variable):
                in_refs.append(("v", a._vid))
                abstract.append(a._data)
            elif isinstance(a, Tensor):
                name = self.capture(a)
                in_refs.append(("p", name))
                abstract.append(jax.ShapeDtypeStruct(
                    tuple(a._data.shape), a._data.dtype))
            else:
                in_refs.append(("c", a))
                abstract.append(a)
        out_struct = jax.eval_shape(
            lambda *xs: fn(*xs, **kwargs), *abstract)
        leaves = _flatten_result(out_struct, has_aux)
        out_vars = [Variable(self.global_block(), l.shape, l.dtype,
                             name=unique_name.generate(op_name))
                    for l in leaves]
        self.nodes.append(OpNode(op_name, fn, kwargs, in_refs,
                                 [v._vid for v in out_vars], has_aux))
        self.version += 1
        return tuple(out_vars) if len(out_vars) > 1 else out_vars[0]

    def record_assign(self, tensor, var):
        name = self.capture(tensor)
        self.nodes.append(AssignNode(name, var._vid))
        self.version += 1

    def rng_key_var(self):
        """A symbolic PRNG key replaced with a fresh key at every run
        (stochastic ops in graphs: dropout etc. — reference dropout_op.cc
        draws per-execution seeds the same way)."""
        import jax.random as jrandom
        struct = jax.eval_shape(lambda: jrandom.key(0))
        v = Variable.__new__(Variable)
        v._data = struct
        v._stop_gradient = True
        v.grad = None
        v._grad_node = None
        v._retain_grad = False
        v.name = unique_name.generate("rng_key")
        v.persistable = False
        v.block = self.global_block()
        v._vid = self._new_vid(v)
        self.rng_vids.append(v._vid)
        return v

    def _find_backward(self):
        for n in self.nodes:
            if isinstance(n, BackwardNode):
                return n
        return None

    def __repr__(self):
        kinds = [type(n).__name__ if not isinstance(n, OpNode) else n.op_name
                 for n in self.nodes]
        return (f"Program(ops={len(self.nodes)}, vars={len(self.vars)}, "
                f"persistables={len(self.captures)})\n  " + " -> ".join(kinds))


def _flatten_result(res, has_aux):
    if has_aux:
        out, aux = res
        return _leaves(out) + _leaves(aux)
    return _leaves(res)


def _leaves(o):
    return list(o) if isinstance(o, (tuple, list)) else [o]


# ---------------------------------------------------------------------------
# default programs + guards (reference: framework.py default_main_program)

_default_main = [Program()]
_default_startup = [Program()]


def default_main_program():
    return _default_main[0]


def default_startup_program():
    return _default_startup[0]


class program_guard:
    def __init__(self, main_program=None, startup_program=None):
        self._main = main_program
        self._startup = startup_program

    def __enter__(self):
        if self._main is not None:
            self._old_main = _default_main[0]
            _default_main[0] = self._main
        if self._startup is not None:
            self._old_startup = _default_startup[0]
            _default_startup[0] = self._startup
        return self

    def __exit__(self, *a):
        if self._main is not None:
            _default_main[0] = self._old_main
        if self._startup is not None:
            _default_startup[0] = self._old_startup
        return False


# ---------------------------------------------------------------------------
# dispatch hook (installed into core.dispatch at import)

def _record_hook(op_name, fn, args, kwargs, has_aux):
    """Called by core.dispatch.primitive while static mode is enabled."""
    if not any(isinstance(a, Variable) for a in args):
        return NotImplemented    # pure-eager subexpression (e.g. param init)
    return default_main_program().record_call(
        op_name, fn, args, kwargs, has_aux)


# -- graph inputs -----------------------------------------------------------

def data(name, shape, dtype="float32", lod_level=0):
    """paddle.static.data (reference: fluid/data.py).

    XLA requires static shapes, and op wrappers bake input shapes into
    attributes at graph-build time (e.g. dropout mask shapes), so dynamic
    (None/-1) dims are rejected rather than silently guessed.  Declare the
    full batch shape; feeding a different batch size recompiles, matching
    XLA's per-shape compilation model.
    """
    if any(s is None or (isinstance(s, int) and s < 0) for s in shape):
        raise ValueError(
            f"static.data('{name}', shape={shape}): dynamic dims "
            "(None/-1) are not supported on the TPU backend — declare the "
            "concrete batch size (different sizes recompile per shape)")
    prog = default_main_program()
    v = Variable(prog.global_block(), shape, dtype, name=name)
    prog.feed_vars[name] = v
    return v


# -- scope ------------------------------------------------------------------

class _ScopeVarHandle:
    def __init__(self, tensor):
        self._t = tensor

    def get_tensor(self):
        return self._t.numpy()

    def set(self, value, place=None):
        self._t.set_value(np.asarray(value))


class Scope:
    """reference framework/scope.h:52 — name → persistable lookup."""

    def find_var(self, name):
        prog = default_main_program()
        if name in prog.captures:
            return _ScopeVarHandle(prog.captures[name])
        return None

    def var(self, name):
        return self.find_var(name)


_global_scope = Scope()


def global_scope():
    return _global_scope


def scope_guard(scope):
    import contextlib
    return contextlib.nullcontext(scope)


# -- autodiff ---------------------------------------------------------------

def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """reference fluid/backward.py:1337 — returns [(param, grad_var)].

    parameter_list entries may be persistable Tensors (parameters), names,
    or symbolic Variables (grad w.r.t. an input / intermediate value).
    """
    prog = default_main_program()
    if not isinstance(loss, Variable):
        raise TypeError("append_backward expects a symbolic loss Variable")
    names, sym_vars = [], []
    if parameter_list is not None:
        for p in parameter_list:
            if isinstance(p, Variable):
                sym_vars.append(p)
            elif isinstance(p, str):
                names.append(p)
            else:
                names.append(prog.capture(p))
    else:
        # only parameters in the loss's dependency cone (reference
        # append_backward walks the grad graph; unrelated params must not
        # receive zero-grad updates / weight decay)
        reachable = _reachable_captures(prog, loss._vid)
        names = [n for n, t in prog.captures.items()
                 if t.trainable and n in reachable]
    grad_vids, var_vids, pairs = {}, {}, []
    for n in names:
        t = prog.captures[n]
        gv = Variable(prog.global_block(), t._data.shape, t._data.dtype,
                      name=n + "@GRAD")
        grad_vids[n] = gv._vid
        pairs.append((t, gv))
    for v in sym_vars:
        gv = Variable(prog.global_block(), v._data.shape, v._data.dtype,
                      name=v.name + "@GRAD")
        var_vids[v._vid] = gv._vid
        pairs.append((v, gv))
    prog.nodes.append(BackwardNode(loss._vid, names, grad_vids, var_vids))
    prog.version += 1
    return pairs


def _reachable_captures(prog, loss_vid):
    """Capture names in the dependency cone of ``loss_vid``."""
    producer = {}
    for node in prog.nodes:
        if isinstance(node, OpNode):
            for vid in node.out_vids:
                producer[vid] = node
    reachable, stack, seen = set(), [loss_vid], set()
    while stack:
        vid = stack.pop()
        if vid in seen:
            continue
        seen.add(vid)
        node = producer.get(vid)
        if node is None:
            continue
        for kind, ref in node.in_refs:
            if kind == "v":
                stack.append(ref)
            elif kind == "p":
                reachable.add(ref)
    return reachable


def append_optimize(optimizer, loss, param_grad_pairs):
    """Record optimizer updates (used by Optimizer.minimize in static mode)."""
    prog = default_main_program()
    bw = prog._find_backward()
    assert bw is not None
    entries = []
    for param, gvar in param_grad_pairs:
        pname = prog.capture(param)
        state = optimizer._init_state(param)
        slot_names = {}
        for slot, arr in state.items():
            st = Tensor(arr, stop_gradient=True,
                        name=f"{pname}@{slot}")
            st.persistable = True
            slot_names[slot] = prog.capture(st)
        entries.append((pname, gvar._vid, slot_names))
    prog.nodes.append(OptimizeNode(optimizer, entries))
    prog.version += 1
