"""Inference-model export/import for static programs.

Reference parity: ``fluid/io.py`` ``save_inference_model:1199`` /
``load_inference_model:1412`` — trim the program to the feed→fetch subgraph
and persist program + params.  TPU-native: the trimmed graph is composed
into one pure function (parameters baked as constants) and serialized as a
StableHLO artifact via ``jax.export``; XLA replaces the reference's
inference Analyzer/IR-pass pipeline (``analysis_predictor.cc:582``).

Artifacts per prefix:
  ``<prefix>.pdmodel``   serialized StableHLO (versioned, stable)
  ``<prefix>.pdiparams`` pickled persistables (for re-export / warm start)
  ``<prefix>.pdmeta``    feed names/specs + fetch arity
"""
from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from . import program as prog_mod
from .program import OpNode, _flatten_result


class InferenceProgram:
    """Loaded inference artifact; runnable via ``Executor.run`` (reference
    returns a pruned Program from load_inference_model)."""

    def __init__(self, exported, feed_names, feed_specs, n_fetch):
        self.exported = exported
        self.feed_names = list(feed_names)
        self.feed_specs = feed_specs
        self.n_fetch = n_fetch

    def run(self, feed: dict):
        arrays = [jnp.asarray(feed[n]) for n in self.feed_names]
        return list(self.exported.call(*arrays))

    # Program-facade bits so generic code can hold it
    def clone(self, for_test=True):
        return self

    def global_block(self):
        return self


def _compose_inference(program, feed_vars, fetch_vars):
    """Pure fn(feed arrays...) -> fetch arrays; persistables baked in.

    Prunes to the feed→fetch cone (reference: save_inference_model trims
    the program to the inference subgraph, fluid/io.py:1199) so training
    nodes (loss, labels, optimizer inputs) never leak into the export.
    """
    feed_vids = [v._vid for v in feed_vars]
    fetch_vids = [v._vid for v in fetch_vars]
    producer = {}
    for n in program.nodes:
        if isinstance(n, OpNode):
            for vid in n.out_vids:
                producer[vid] = n
    needed, stack = set(), list(fetch_vids)
    while stack:
        vid = stack.pop()
        if vid in needed or vid in feed_vids:
            continue
        needed.add(vid)
        node = producer.get(vid)
        if node is not None:
            for kind, ref in node.in_refs:
                if kind == "v":
                    stack.append(ref)
    nodes = [n for n in program.nodes if isinstance(n, OpNode)
             and any(v in needed for v in n.out_vids)]
    caps = {n: t._data for n, t in program.captures.items()}
    rng_vids = list(program.rng_vids)

    def fn(*feed_arrays):
        env = dict(zip(feed_vids, feed_arrays))
        # inference: stochastic ops get a fixed key (dropout should be
        # built with is_test=True; this keeps the export well-defined)
        for i, vid in enumerate(rng_vids):
            env[vid] = jax.random.fold_in(jax.random.key(0), i)
        for node in nodes:
            args = []
            for kind, ref in node.in_refs:
                if kind == "v":
                    args.append(env[ref])
                elif kind == "p":
                    args.append(caps[ref])
                else:
                    args.append(ref)
            res = node.fn(*args, **node.kwargs)
            for vid, leaf in zip(node.out_vids,
                                 _flatten_result(res, node.has_aux)):
                env[vid] = leaf
        return [env[v] for v in fetch_vids]

    return fn


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """paddle.static.save_inference_model (reference fluid/io.py:1199)."""
    program = program or prog_mod.default_main_program()
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) \
        else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    fn = _compose_inference(program, feed_vars, fetch_vars)
    specs = [jax.ShapeDtypeStruct(tuple(v._data.shape), v._data.dtype)
             for v in feed_vars]
    exported = jax.export.export(jax.jit(fn))(*specs)
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump({n: np.asarray(t._data)
                     for n, t in program.captures.items()}, f, protocol=4)
    meta = {
        "feed_names": [v.name for v in feed_vars],
        "feed_specs": [(list(s.shape), str(s.dtype)) for s in specs],
        "n_fetch": len(fetch_vars),
        "kind": "static_inference",
    }
    with open(path_prefix + ".pdmeta", "wb") as f:
        pickle.dump(meta, f, protocol=4)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns [InferenceProgram, feed_names, fetch_indices] (reference
    fluid/io.py:1412 returns [program, feed_names, fetch_targets])."""
    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = jax.export.deserialize(f.read())
    with open(path_prefix + ".pdmeta", "rb") as f:
        meta = pickle.load(f)
    prog = InferenceProgram(exported, meta["feed_names"],
                            meta["feed_specs"], meta["n_fetch"])
    return [prog, prog.feed_names, list(range(prog.n_fetch))]
