"""Static-mode compatibility surface.

Reference parity: the remaining ``paddle.static`` exports —
CompiledProgram/BuildStrategy/ExecutionStrategy/ParallelExecutor
(``fluid/compiler.py``, ``details/build_strategy.cc``), place lists,
``device_guard``, program/persistable (de)serialization (``static/io.py``),
program-state save/load, and the static metric ops (accuracy/auc).

On TPU these knobs have one honest mapping: XLA already performs the
optimizations BuildStrategy toggles pick between, so the strategy objects
are accepted and recorded but do not change compilation; CompiledProgram
is the same Program with a strategy attached.
"""
from __future__ import annotations

import pickle

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import primitive, ensure_tensor
from ..core.tensor import Tensor
from ..nn.param_attr import ParamAttr
from . import program as prog_mod


class BuildStrategy:
    """reference: details/build_strategy.h (pybind surface)."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.enable_inplace = True
        self.memory_optimize = True
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    """reference: details/execution_strategy.h."""

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 10
        self.num_iteration_per_run = 1


class CompiledProgram:
    """reference: fluid/compiler.py:164 — XLA is the compiler, so this
    carries the program + strategies; Executor.run unwraps it."""

    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = None
        self._places = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._exec_strategy = exec_strategy
        self._places = places
        return self


class ParallelExecutor:
    """Legacy multi-device runner (reference parallel_executor.cc:609);
    delegates to Executor — device parallelism comes from shardings."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 build_strategy=None, exec_strategy=None, scope=None,
                 share_vars_from=None):
        from .executor import Executor
        self._exe = Executor()
        self._program = main_program

    def run(self, fetch_list=None, feed=None, return_numpy=True):
        return self._exe.run(self._program, feed=feed,
                             fetch_list=fetch_list,
                             return_numpy=return_numpy)


# -- places ----------------------------------------------------------------

def cpu_places(device_count=None):
    from ..core import device as device_mod
    n = device_count or 1
    return [device_mod.current_place() for _ in range(n)]


def cuda_places(device_ids=None):
    import jax as _jax
    ids = device_ids if device_ids is not None else \
        range(len(_jax.devices()))
    from ..core import device as device_mod
    return [device_mod.current_place() for _ in ids]


xpu_places = cuda_places


class device_guard:
    """reference: fluid/framework.py device_guard — placement hints are
    XLA's job; accepted and ignored."""

    def __init__(self, device=None):
        self.device = device

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class WeightNormParamAttr(ParamAttr):
    """reference: fluid/param_attr.py WeightNormParamAttr."""

    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim


# -- static metric ops ------------------------------------------------------

def accuracy(input, label, k=1, correct=None, total=None):
    """reference: metrics/accuracy_op.cc — top-k accuracy as a graph op."""
    input, label = ensure_tensor(input), ensure_tensor(label)

    @primitive(name="accuracy", nondiff=(0, 1))
    def _acc(x, y):
        topk = jnp.argsort(-x, axis=-1)[..., :k]
        hit = jnp.any(topk == y.reshape(-1, 1), axis=-1)
        return jnp.mean(hit.astype(jnp.float32))

    return _acc(input, label)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """reference: metrics/auc_op.cc — batch AUC (the reference's global
    accumulator states live in scope vars; here each call computes the
    batch statistic, matching the common fetch usage)."""
    input, label = ensure_tensor(input), ensure_tensor(label)

    @primitive(name="auc", nondiff=(0, 1))
    def _auc(x, y):
        pos_score = x[:, 1] if x.ndim == 2 and x.shape[1] == 2 else \
            x.reshape(-1)
        y = y.reshape(-1).astype(jnp.float32)
        thresholds = jnp.linspace(0.0, 1.0, num_thresholds)
        pred_pos = pos_score[None, :] >= thresholds[:, None]
        tp = jnp.sum(pred_pos * y[None, :], axis=1)
        fp = jnp.sum(pred_pos * (1 - y)[None, :], axis=1)
        P = jnp.maximum(jnp.sum(y), 1e-6)
        N = jnp.maximum(jnp.sum(1 - y), 1e-6)
        tpr = tp / P
        fpr = fp / N
        return -jnp.trapezoid(tpr, fpr)

    return _auc(input, label)


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """reference: print_op.cc — identity that prints at execution."""
    input = ensure_tensor(input)
    msg = message or "Print"

    @primitive(name="print")
    def _print(x):
        jax.debug.print(msg + ": {}", x)
        return x

    return _print(input)


# -- (de)serialization (reference: static/io.py serialize_*) ----------------

def serialize_program(feed_vars, fetch_vars, **kwargs):
    from .io import _compose_inference
    prog = prog_mod.default_main_program()
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) \
        else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    fn = _compose_inference(prog, feed_vars, fetch_vars)
    specs = [jax.ShapeDtypeStruct(tuple(v._data.shape), v._data.dtype)
             for v in feed_vars]
    exported = jax.export.export(jax.jit(fn))(*specs)
    header = pickle.dumps({
        "feed_names": [v.name for v in feed_vars],
        "n_fetch": len(fetch_vars)})
    return len(header).to_bytes(8, "little") + header + \
        exported.serialize()


def deserialize_program(data):
    from .io import InferenceProgram
    hlen = int.from_bytes(data[:8], "little")
    header = pickle.loads(data[8:8 + hlen])
    exported = jax.export.deserialize(data[8 + hlen:])
    return InferenceProgram(exported, header["feed_names"], None,
                            header["n_fetch"])


def serialize_persistables(feed_vars, fetch_vars, **kwargs):
    prog = prog_mod.default_main_program()
    return pickle.dumps({n: np.asarray(t._data)
                         for n, t in prog.captures.items()})


def deserialize_persistables(program, data, executor=None):
    state = pickle.loads(data)
    for n, t in program.captures.items():
        if n in state:
            t.set_value(state[n])


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


# -- program state (reference: static/io.py load/set_program_state) ---------

def load_program_state(model_path, var_list=None):
    path = model_path if model_path.endswith(".pdparams") else \
        model_path + ".pdparams"
    with open(path, "rb") as f:
        return pickle.load(f)


def set_program_state(program, state):
    for n, t in program.captures.items():
        if n in state:
            t.set_value(state[n])


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    import os
    prog = main_program or prog_mod.default_main_program()
    os.makedirs(dirname, exist_ok=True)
    names = [v if isinstance(v, str) else v.name for v in (vars or [])] \
        or list(prog.captures)
    state = {n: np.asarray(prog.captures[n]._data) for n in names
             if n in prog.captures}
    with open(os.path.join(dirname, filename or "vars.pdparams"),
              "wb") as f:
        pickle.dump(state, f, protocol=4)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    import os
    prog = main_program or prog_mod.default_main_program()
    with open(os.path.join(dirname, filename or "vars.pdparams"),
              "rb") as f:
        state = pickle.load(f)
    for n, t in prog.captures.items():
        if n in state:
            t.set_value(state[n])
