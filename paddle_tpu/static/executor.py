"""Static-graph Executor.

Reference parity: ``fluid/executor.py:475`` → ``framework/executor.cc:166``
(sequential op interpreter with scope GC) and ``parallel_executor.cc:609``
(multi-device SSA runtime).  TPU-native: the recorded node list is composed
into ONE Python function and ``jax.jit``-compiled — XLA does the scheduling,
fusion, memory planning and (through shardings) the multi-device work that
the reference spread across Executor/ParallelExecutor/142 IR passes.
Compiled programs are cached per (program version, feed signature), the
analogue of the reference's program cache (``executor.py:1160-1186``).

Gradient nodes are handled by replaying the op prefix under
``jax.value_and_grad`` — duplicated pure subcomputations are CSE'd by XLA,
so the compiled artifact matches what a hand-fused step would produce.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core import rng as rng_mod
from . import program as prog_mod
from .program import (Program, Variable, OpNode, AssignNode, BackwardNode,
                      OptimizeNode, _flatten_result)


class Executor:
    """paddle.static.Executor (place is advisory: XLA owns placement)."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    # ------------------------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True):
        if program is None:
            program = prog_mod.default_main_program()
        from .compat import CompiledProgram
        if isinstance(program, CompiledProgram):
            program = program._program    # XLA is the compiler already
        feed = feed or {}
        fetch_list = fetch_list or []
        from .io import InferenceProgram
        if isinstance(program, InferenceProgram):
            outs = program.run(feed)
            if fetch_list:
                for i in fetch_list:
                    if not isinstance(i, (int, np.integer)):
                        raise TypeError(
                            "fetch_list for a loaded inference program "
                            "holds output indices (as returned by "
                            f"load_inference_model), got {type(i).__name__}")
                outs = [outs[int(i)] for i in fetch_list]
            return [np.asarray(o) for o in outs] if return_numpy else \
                [Tensor(o) for o in outs]
        if not program.nodes and not fetch_list:
            return []          # e.g. startup program: params already init'd

        feed_arrays = {}
        for name, value in feed.items():
            if isinstance(value, Tensor):
                value = value._data
            feed_arrays[name] = jnp.asarray(value)

        fetch_refs = []
        for f in fetch_list:
            if isinstance(f, str):
                f = program.var(f)
            if isinstance(f, Variable):
                fetch_refs.append(("v", f._vid))
            else:  # persistable (parameter/buffer) fetched by name/handle
                fetch_refs.append(("p", program.capture(f)))

        # keyed on the Program OBJECT (kept alive by the cache) so a reused
        # id() can never alias a dead program's compiled artifact
        key = (program, program.version,
               tuple(sorted((n, tuple(a.shape), str(a.dtype))
                            for n, a in feed_arrays.items())),
               tuple(fetch_refs))
        if key not in self._cache:
            self._cache[key] = self._compose(program, fetch_refs)
        fn = self._cache[key]

        cap_names = sorted(program.captures)
        captures = {n: program.captures[n]._data for n in cap_names}
        lrs = tuple(jnp.asarray(n.optimizer.get_lr(), jnp.float32)
                    for n in program.nodes if isinstance(n, OptimizeNode))
        # draw from the global stream only if the program has stochastic
        # ops — a deterministic program must not perturb the RNG sequence
        rkey = rng_mod.next_key() if program.rng_vids else \
            jax.random.key(0)
        fetches, updated = fn(feed_arrays, captures, lrs, rkey)

        for name, arr in updated.items():
            program.captures[name]._data = arr
        for n in program.nodes:
            if isinstance(n, OptimizeNode):
                n.optimizer._step_count += 1
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    # ------------------------------------------------------------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """reference: executor.py:1427 _run_from_dataset → the C++ Trainer/
        DeviceWorker path (trainer.h:53, device_worker.h).  TPU-native: the
        native dataset engine gathers batches off the GIL; each batch runs
        through the same compiled program as Executor.run."""
        if program is None:
            program = prog_mod.default_main_program()
        if dataset is None:
            raise ValueError("train_from_dataset requires a dataset")
        feed_names = [v.name for v in dataset._use_vars]
        results = []
        for step, slots in enumerate(dataset):
            feed = dict(zip(feed_names, slots))
            out = self.run(program, feed=feed, fetch_list=fetch_list)
            if fetch_list and debug and step % print_period == 0:
                print(f"step {step}:", [np.asarray(o).mean() for o in out])
            if fetch_list:
                results.append(out)
        return results

    def infer_from_dataset(self, program=None, dataset=None, **kwargs):
        return self.train_from_dataset(program, dataset, **kwargs)

    # ------------------------------------------------------------------
    def _compose(self, program, fetch_refs):
        nodes = list(program.nodes)
        feed_vids = {name: v._vid for name, v in program.feed_vars.items()}
        rng_vids = list(program.rng_vids)

        def run_op(node, env, caps):
            args = []
            for kind, ref in node.in_refs:
                if kind == "v":
                    args.append(env[ref])
                elif kind == "p":
                    args.append(caps[ref])
                else:
                    args.append(ref)
            res = node.fn(*args, **node.kwargs)
            for vid, leaf in zip(node.out_vids,
                                 _flatten_result(res, node.has_aux)):
                env[vid] = leaf

        def composed(feeds, caps, lrs, rkey):
            env = {}
            for name, vid in feed_vids.items():
                if name in feeds:
                    env[vid] = feeds[name]
            for i, vid in enumerate(rng_vids):
                env[vid] = jax.random.fold_in(rkey, i)
            updated = {}
            opt_i = 0

            def caps_view():
                return {**caps, **updated}

            for idx, node in enumerate(nodes):
                if isinstance(node, OpNode):
                    run_op(node, env, caps_view())
                elif isinstance(node, AssignNode):
                    updated[node.capture_name] = env[node.vid]
                elif isinstance(node, BackwardNode):
                    prefix = [n for n in nodes[:idx]
                              if isinstance(n, OpNode)]
                    base_caps = caps_view()
                    seed_vals = {vid: env[vid]
                                 for vid in node.var_vids}
                    base_env = {vid: env[vid]
                                for vid in feed_vids.values()
                                if vid in env}
                    for vid in rng_vids:
                        base_env[vid] = env[vid]

                    def fwd(train_caps, var_vals, _node=node,
                            _prefix=prefix, _base_caps=base_caps,
                            _base_env=base_env):
                        env2 = dict(_base_env)
                        env2.update(var_vals)
                        caps2 = {**_base_caps, **train_caps}
                        for n in _prefix:
                            # an op whose outputs are all grad seeds is
                            # cut: the seed is the independent input
                            if n.out_vids and all(v in var_vals
                                                  for v in n.out_vids):
                                continue
                            run_op(n, env2, caps2)
                        return env2[_node.loss_vid]

                    train_caps = {n: base_caps[n]
                                  for n in node.param_names}
                    _, (g_caps, g_vars) = jax.value_and_grad(
                        fwd, argnums=(0, 1))(train_caps, seed_vals)
                    for pname, gvid in node.grad_vids.items():
                        env[gvid] = g_caps[pname]
                    for vid, gvid in node.var_vids.items():
                        env[gvid] = g_vars[vid]
                elif isinstance(node, OptimizeNode):
                    lr = lrs[opt_i]
                    opt_i += 1
                    opt = node.optimizer
                    cv = caps_view()
                    grads_list = [env[gv] for _, gv, _ in node.entries]
                    if opt._grad_clip is not None:
                        grads_list = opt._grad_clip.apply_tree(grads_list)
                    for (pname, gvid, slots), g in zip(node.entries,
                                                       grads_list):
                        p = cv[pname]
                        state = {sl: cv[cn] for sl, cn in slots.items()}
                        new_p, new_state = opt._update(p, g, state, lr)
                        updated[pname] = new_p
                        for sl, cn in slots.items():
                            updated[cn] = new_state[sl]

            outs = []
            cv = caps_view()
            for kind, ref in fetch_refs:
                outs.append(env[ref] if kind == "v" else cv[ref])
            return outs, updated

        return jax.jit(composed)


# ---------------------------------------------------------------------------
# save / load of persistables (reference fluid/io.py save_persistables:621)

def save(program, model_path, protocol=4):
    import pickle
    state = {n: np.asarray(t._data) for n, t in program.captures.items()}
    with open(model_path + ".pdparams" if not model_path.endswith(
            ".pdparams") else model_path, "wb") as f:
        pickle.dump(state, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    import pickle
    path = model_path if model_path.endswith(".pdparams") else \
        model_path + ".pdparams"
    with open(path, "rb") as f:
        state = pickle.load(f)
    for n, t in program.captures.items():
        if n in state:
            t.set_value(state[n])
