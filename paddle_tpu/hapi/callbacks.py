"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import os
import time


class Callback:
    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                               else f"{k}: {v}"
                               for k, v in (logs or {}).items())
            total = self.steps if self.steps else "?"
            print(f"Epoch {self.epoch}: step {step}/{total} - {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float)
                               else f"{k}: {v}"
                               for k, v in (logs or {}).items())
            print(f"Epoch {epoch} done ({dt:.1f}s) - {items}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            items = " - ".join(f"{k}: {v}" for k, v in (logs or {}).items())
            print(f"Eval - {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0,
                 verbose=1, min_delta=0, baseline=None,
                 save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.best = None
        self.wait = 0
        self.stopped_epoch = 0
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        from ..optimizer.lr import LRScheduler as Sched
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s and self.by_epoch:
            s.step()


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     verbose=2, save_freq=1, save_dir=None, metrics=None):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(verbose=verbose))
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    params = {"epochs": epochs, "steps": steps, "verbose": verbose,
              "metrics": metrics or []}
    for c in cbks:
        c.set_params(params)
        c.set_model(model)
    return CallbackList(cbks)


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = callbacks

    def __getattr__(self, name):
        def call(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, name)(*args, **kwargs)
        return call


class ReduceLROnPlateau(Callback):
    """reference: hapi/callbacks.py ReduceLROnPlateau — shrink LR when the
    monitored metric stops improving."""

    def __init__(self, monitor="loss", factor=0.1, patience=10,
                 verbose=1, mode="auto", min_delta=1e-4, cooldown=0,
                 min_lr=0):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        lower_better = mode == "min" or (mode == "auto"
                                         and "acc" not in monitor)
        self._better = ((lambda a, b: a < b - min_delta) if lower_better
                        else (lambda a, b: a > b + min_delta))
        self._best = None
        self._wait = 0
        self._cooldown_left = 0

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(cur)
        if self._best is None or self._better(cur, self._best):
            self._best = cur
            self._wait = 0
            return
        if self._cooldown_left > 0:
            # patience accounting pauses while the reduced LR takes effect
            self._cooldown_left -= 1
            self._wait = 0
            return
        self._wait += 1
        if self._wait >= self.patience:
            opt = self.model._optimizer
            old = float(opt.get_lr())
            new = max(old * self.factor, self.min_lr)
            if new < old:
                opt.set_lr(new)
                if self.verbose:
                    print(f"Epoch {epoch}: ReduceLROnPlateau reducing "
                          f"learning rate to {new}.")
            self._cooldown_left = self.cooldown
            self._wait = 0


class VisualDL(Callback):
    """reference: hapi/callbacks.py VisualDLCallback.  The visualdl
    package is not vendored; scalars stream to JSON-lines under log_dir
    (one record per step/epoch), which its UI and any reader can ingest."""

    def __init__(self, log_dir="./log"):
        self.log_dir = log_dir
        self._fh = None
        self._step = 0

    def on_train_begin(self, logs=None):
        import os
        os.makedirs(self.log_dir, exist_ok=True)
        self._fh = open(os.path.join(self.log_dir, "scalars.jsonl"), "a")

    def _write(self, tag, logs):
        import json as _json
        if not self._fh or not logs:
            return
        rec = {"step": self._step, "tag": tag}
        rec.update({k: float(v) for k, v in logs.items()
                    if isinstance(v, (int, float))})
        self._fh.write(_json.dumps(rec) + "\n")
        self._fh.flush()

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if self._step % 10 == 0:
            self._write("train", logs)

    def on_epoch_end(self, epoch, logs=None):
        self._write("epoch", logs)

    def on_train_end(self, logs=None):
        if self._fh:
            self._fh.close()
            self._fh = None
