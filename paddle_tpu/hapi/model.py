"""paddle.Model — the high-level train loop.

Reference parity: ``python/paddle/hapi/model.py:810`` (Model.prepare/fit/
evaluate/predict/save/load, DynamicGraphAdapter vs StaticGraphAdapter).

TPU-native design: there is only ONE adapter — the compiled-step path.
``prepare`` wires a TrainStep (parallel/train_step.py); ``fit`` feeds it
host batches; the whole forward+backward+update is a single pjit'd XLA
program per batch shape (this is the role the StaticGraphAdapter's
Program+Executor played, with dygraph ergonomics preserved).
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..core import autograd
from ..io import DataLoader
from ..parallel.train_step import TrainStep
from . import callbacks as cbks_mod


def _metric_to_host(x):
    """Metric inputs from a multi-host mesh are globally sharded — no
    single process can np.asarray them; allgather the global value."""
    import jax
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(x,
                                                            tiled=True))
    return np.asarray(x)


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step = None
        self.stop_training = False
        self._eval_fn = None
        self._mode = "train"
        self._eval_cache = {}

    @property
    def mode(self):
        """reference: hapi/model.py:256 — 'train' / 'eval' / 'test'."""
        return self._mode

    @mode.setter
    def mode(self, value):
        self._mode = value
        if value == "train":
            self.network.train()
        else:
            self.network.eval()

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, strategy=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
        amp_level = None
        if amp_configs:
            amp_level = amp_configs.get("level", "O1") if isinstance(
                amp_configs, dict) else "O1"
        self._strategy = strategy
        self._amp_level = amp_level
        if optimizer is not None:
            self._train_step = TrainStep(
                self.network, optimizer, loss_fn=loss, strategy=strategy,
                amp_level=amp_level, metrics=self._metrics)
        return self

    # ------------------------------------------------------------------
    @staticmethod
    def _split_batch(batch):
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                return [batch[0]], list(batch[1:])
            return [batch[0]], []
        return [batch], []

    def train_batch(self, inputs, labels=None, update=True):
        """One compiled train step on a batch (reference: model.py:896).
        Metrics are computed INSIDE the compiled step (model.py:1495
        threads prepared metrics through train) and accumulated here."""
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is not None else []
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        loss = self._train_step.step(list(inputs), list(labels))
        metrics_out = []
        for m, mo in zip(self._metrics,
                         self._train_step.last_metric_outs):
            m.update(*[_metric_to_host(x) for x in mo])
            metrics_out.append(m.accumulate())
        return [float(loss.numpy())] + metrics_out

    def _forward_eval(self, inputs):
        """Compiled eval forward (the role of the reference
        StaticGraphAdapter's eval program): one jax.jit per input shape,
        params passed as arguments so weight updates never retrace.
        Falls back to eager for untraceable forwards."""
        import jax
        import jax.numpy as jnp
        from ..jit import functional_call, _wrap_tree

        net = self.network
        params = {k: p._data for k, p in net.named_parameters()}
        buffers = {k: b._data for k, b in net.named_buffers()
                   if b is not None}
        try:
            arrays = [i._data if isinstance(i, Tensor)
                      else jnp.asarray(i) for i in inputs]
        except Exception:
            return None
        key = tuple((tuple(a.shape), str(a.dtype)) for a in arrays) + (
            len(params), len(buffers))
        if self._eval_cache.get(key) == "untraceable":
            return None  # don't pay a failing re-trace per batch
        if key not in self._eval_cache:
            pn, bn = sorted(params), sorted(buffers)

            @jax.jit
            def fwd(p_list, b_list, xs):
                with autograd.no_grad():
                    out, _ = functional_call(
                        net, dict(zip(pn, p_list)),
                        dict(zip(bn, b_list)), xs, training=False)
                return out
            self._eval_cache[key] = (fwd, pn, bn)
        fwd, pn, bn = self._eval_cache[key]
        try:
            out = fwd([params[k] for k in pn],
                      [buffers[k] for k in bn], arrays)
        except Exception:
            # mark untraceable ONLY if this shape never succeeded — a
            # transient runtime failure (device busy/OOM) on a working
            # compiled fn must not permanently disable the jit path
            if key not in getattr(self, "_eval_ok", set()):
                self._eval_cache[key] = "untraceable"
            return None
        if not hasattr(self, "_eval_ok"):
            self._eval_ok = set()
        self._eval_ok.add(key)
        return _wrap_tree(out)

    def eval_batch(self, inputs, labels=None):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if labels is not None else []
        labels = labels if isinstance(labels, (list, tuple)) else [labels]
        self._sync_weights()
        prev = self.mode
        self.mode = "eval"
        out = self._forward_eval(inputs)
        if out is None:  # untraceable forward: eager fallback
            with autograd.no_grad():
                out = self.network(*inputs)
        losses = []
        if self._loss is not None and labels:
            loss = self._loss(out, *labels)
            losses.append(float(loss.numpy()))
        for m in self._metrics:
            m.update(*to_list(m.compute(out, *labels)))
        self.mode = prev
        return losses, out

    def predict_batch(self, inputs):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self._sync_weights()
        prev = self.mode
        self.mode = "test"
        out = self._forward_eval(inputs)
        if out is None:
            with autograd.no_grad():
                out = self.network(*inputs)
        self.mode = prev
        return out

    def _sync_weights(self):
        if self._train_step is not None:
            self._train_step.sync_to_layer()

    # ------------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None):
        assert self._train_step is not None, "call prepare() first"
        if isinstance(train_data, DataLoader):
            loader = train_data
        else:
            loader = DataLoader(train_data, batch_size=batch_size,
                                shuffle=shuffle, drop_last=drop_last,
                                num_workers=num_workers)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbks = cbks_mod.config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps,
            verbose=verbose, save_freq=save_freq, save_dir=save_dir,
            metrics=[m.name() for m in self._metrics])
        # async device prefetch (reference: buffered_reader.cc double
        # buffer): batches are already en route to the mesh, pre-placed
        # with the step's data sharding, while the previous step runs
        import jax as _jax
        feed = loader
        if _jax.process_count() == 1 and not self._train_step.is_pipeline:
            from ..io import DeviceLoader
            feed = DeviceLoader(
                loader, buffer_size=2,
                sharding_fn=self._train_step._data_sharding)
        self.stop_training = False
        cbks.on_train_begin()
        it = 0
        for epoch in range(epochs):
            if hasattr(loader, "batch_sampler") and hasattr(
                    loader.batch_sampler, "set_epoch"):
                loader.batch_sampler.set_epoch(epoch)
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            last_logs = {}
            for step, batch in enumerate(feed):
                cbks.on_train_batch_begin(step)
                ins, labs = self._split_batch(batch)
                loss = self._train_step.step(ins, labs)
                last_logs = {"loss": float(loss.numpy()),
                             "lr": self._optimizer.get_lr()}
                for m, mo in zip(self._metrics,
                                 self._train_step.last_metric_outs):
                    m.update(*[_metric_to_host(x) for x in mo])
                    names, vals = m.name(), m.accumulate()
                    if not isinstance(names, (list, tuple)):
                        names, vals = [names], [vals]
                    if not isinstance(vals, (list, tuple)):
                        vals = [vals]
                    last_logs.update(dict(zip(names, vals)))
                cbks.on_train_batch_end(step, last_logs)
                it += 1
                if num_iters is not None and it >= num_iters:
                    self.stop_training = True
                    break
            cbks.on_epoch_end(epoch, last_logs)
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_data,
                                          batch_size=batch_size,
                                          verbose=0,
                                          num_workers=num_workers)
                cbks.on_eval_end(eval_logs)
            if self.stop_training:
                break
        cbks.on_train_end()
        self._sync_weights()

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        if isinstance(eval_data, DataLoader):
            loader = eval_data
        else:
            loader = DataLoader(eval_data, batch_size=batch_size,
                                num_workers=num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            ins, labs = self._split_batch(batch)
            batch_losses, _ = self.eval_batch(ins, labs)
            losses.extend(batch_losses)
        logs = {}
        if losses:
            logs["loss"] = float(np.mean(losses))
        for m in self._metrics:
            names = m.name()
            vals = m.accumulate()
            if not isinstance(names, (list, tuple)):
                names, vals = [names], [vals]
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            logs.update(dict(zip(names, vals)))
        if verbose:
            print("Eval:", logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        if isinstance(test_data, DataLoader):
            loader = test_data
        else:
            loader = DataLoader(test_data, batch_size=batch_size,
                                num_workers=num_workers)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch)
            out = self.predict_batch(ins)
            outputs.append(out.numpy() if isinstance(out, Tensor) else out)
        if stack_outputs:
            return [np.concatenate(outputs)]
        return [outputs]

    # ------------------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io import save as fsave
        self._sync_weights()
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        import os
        from ..framework.io import load as fload
        self.network.set_state_dict(fload(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fload(path + ".pdopt"))
        if self._optimizer is not None:
            # rebuild device state from the restored layer
            self._train_step = TrainStep(
                self.network, self._optimizer, loss_fn=self._loss,
                strategy=getattr(self, "_strategy", None),
                amp_level=getattr(self, "_amp_level", None))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        """Layer-by-layer summary (reference: hapi summary.py prints
        Layer (type), Output Shape, Param #).  With ``input_size`` a
        shape-only eval-mode forward (jax.eval_shape — no FLOPs run)
        records every sublayer's output shape; without it, falls back to
        the parameter table."""
        total = 0
        if input_size is not None:
            import jax
            import jax.numpy as jnp
            from ..jit import functional_call

            shapes = input_size if isinstance(input_size[0],
                                              (list, tuple)) \
                else [input_size]
            dt = jnp.dtype(dtype or "float32")
            net = self.network
            records = []
            handles = []

            def mk_hook(name, layer):
                def hook(lyr, inputs, outputs):
                    out = outputs[0] if isinstance(outputs, (tuple, list)) \
                        else outputs
                    shape = list(getattr(out, "shape", []) or [])
                    n_params = sum(int(np.prod(p.shape))
                                   for p in lyr.parameters(
                                       include_sublayers=False))
                    records.append((name, type(lyr).__name__, shape,
                                    n_params))
                    return outputs
                return hook

            for name, sub in net.named_sublayers():
                handles.append(sub.register_forward_post_hook(
                    mk_hook(name, sub)))
            try:
                params = {k: p._data
                          for k, p in net.named_parameters()}
                buffers = {k: b._data for k, b in net.named_buffers()
                           if b is not None}

                def fwd(p, b, xs):
                    out, _ = functional_call(net, p, b, xs,
                                             training=False)
                    return out

                jax.eval_shape(fwd, params, buffers,
                               [jax.ShapeDtypeStruct(tuple(s), dt)
                                for s in shapes])
            finally:
                for h in handles:
                    h.remove()
            lines = ["-" * 76,
                     f"{'Layer (type)':<36}{'Output Shape':<24}"
                     f"{'Param #':>12}",
                     "=" * 76]
            for name, tname, shape, n_params in records:
                lines.append(f"{name + ' (' + tname + ')':<36}"
                             f"{str(shape):<24}{n_params:>12,}")
        else:
            # no input_size: the per-parameter table
            lines = ["-" * 76,
                     f"{'Parameter':<44}{'Shape':<20}{'Count':>12}",
                     "=" * 76]
            for name, p in self.network.named_parameters():
                lines.append(f"{name:<44}{str(p.shape):<20}"
                             f"{int(np.prod(p.shape)):>12,}")
        for name, p in self.network.named_parameters():
            total += int(np.prod(p.shape))
        trainable = sum(int(np.prod(p.shape))
                        for p in self.network.parameters()
                        if getattr(p, "trainable", True))
        lines += ["=" * 76,
                  f"Total params: {total:,}",
                  f"Trainable params: {trainable:,}",
                  f"Non-trainable params: {total - trainable:,}",
                  "-" * 76]
        text = "\n".join(lines)
        print(text)
        return {"total_params": total, "trainable_params": trainable}

    def flops(self, inputs=None, input_size=None, dtype="float32",
              print_detail=False):
        """FLOPs of one eval-mode forward, from XLA's own cost analysis
        of the compiled program (reference: hapi paddle.flops sums
        per-layer hook estimates; the compiler's count is exact for the
        fused program that actually runs)."""
        import jax
        import jax.numpy as jnp
        from ..jit import functional_call

        if inputs is None:
            if input_size is None:
                raise ValueError("flops: pass example inputs or "
                                 "input_size")
            shapes = input_size if isinstance(input_size[0],
                                              (list, tuple)) \
                else [input_size]
            inputs = [jnp.zeros(tuple(s), jnp.dtype(dtype))
                      for s in shapes]
        else:
            inputs = inputs if isinstance(inputs, (list, tuple)) \
                else [inputs]
            inputs = [i._data if isinstance(i, Tensor)
                      else jnp.asarray(i) for i in inputs]
        net = self.network
        params = {k: p._data for k, p in net.named_parameters()}
        buffers = {k: b._data for k, b in net.named_buffers()
                   if b is not None}

        def fwd(p, b, xs):
            out, _ = functional_call(net, p, b, xs, training=False)
            return out

        lowered = jax.jit(fwd).lower(params, buffers, list(inputs))
        analysis = lowered.compile().cost_analysis() or {}
        total = int(analysis.get("flops", 0))
        if print_detail:
            print(f"FLOPs (XLA cost analysis, eval forward): {total:,}")
        return total


def to_list(value):
    if value is None:
        return []
    if isinstance(value, (list, tuple)):
        return list(value)
    return [value]
