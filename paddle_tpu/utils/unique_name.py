"""unique_name parity (reference: fluid/unique_name.py)."""
from __future__ import annotations

import contextlib

_counters: dict[str, int] = {}
_prefix: list[str] = []


def generate(key):
    _counters[key] = _counters.get(key, 0) + 1
    name = f"{key}_{_counters[key] - 1}"
    if _prefix:
        return "/".join(_prefix) + "/" + name
    return name


@contextlib.contextmanager
def guard(new_generator=None):
    saved = dict(_counters)
    if isinstance(new_generator, str):
        _prefix.append(new_generator)
    try:
        yield
    finally:
        _counters.clear()
        _counters.update(saved)
        if isinstance(new_generator, str):
            _prefix.pop()


def switch(new_generator=None):
    _counters.clear()
