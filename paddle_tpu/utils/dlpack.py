"""paddle.utils.dlpack (reference: framework/dlpack_tensor.cc,
pybind tensor.to_dlpack) — zero-copy tensor exchange.

Modern dlpack exchanges protocol-carrying objects (``__dlpack__`` /
``__dlpack_device__``) rather than raw capsules; ``to_dlpack`` returns the
underlying jax array, which any dlpack consumer (torch, numpy, cupy…)
accepts directly, and ``from_dlpack`` accepts any dlpack-capable object.
"""
from __future__ import annotations

import jax

from ..core.tensor import Tensor


def to_dlpack(x):
    arr = x._data if isinstance(x, Tensor) else x
    return arr  # jax.Array implements __dlpack__/__dlpack_device__


def from_dlpack(data):
    return Tensor(jax.dlpack.from_dlpack(data))
