"""Profiler.

Reference parity: host RecordEvent spans + CUPTI device tracer + chrome
trace export (``platform/profiler.cc:196``, ``device_tracer.cc:57``,
``tools/timeline.py``).  TPU-native: ``jax.profiler`` emits an XPlane trace
(TensorBoard / Perfetto-compatible — the chrome://tracing successor);
RecordEvent maps to ``jax.profiler.TraceAnnotation`` so host spans correlate
with device activity in the same trace.  Host spans are collected through
the SAME span tracer the serving engine uses (``monitor/tracing.py``:
bounded per-thread ring buffers, Catapult-native events), so
``stop_profiler(profile_path=...)`` writes a standalone chrome://tracing
JSON via the shared exporter and prints the reference-style summary table
(sorted by total time) without TensorBoard.
"""
from __future__ import annotations

import contextlib
import json
import os
import time

import jax

from ..monitor import tracing as _tracing

# The profiler's collection backend: one process-wide tracer, muted
# until start_profiler() arms it.  annotate=True keeps the historical
# behavior of entering a jax.profiler.TraceAnnotation per span (so
# RecordEvent shows up in XPlane captures even outside start/stop).
_CAPACITY = 1 << 20  # profiling sessions are short; keep every span
_tracer = _tracing.Tracer(capacity=_CAPACITY, enabled=False,
                          annotate=True)


class RecordEvent(_tracing.RecordEvent):
    """RAII span (reference: platform/profiler.h RecordEvent) —
    collected by the shared monitor tracer while profiling is active,
    always annotated into any live XPlane capture."""

    def __init__(self, name):
        super().__init__(name, tracer=_tracer, cat="host",
                         annotate=True)


_active_dir = None


def start_profiler(state="All", tracer_option="Default",
                   log_dir="/tmp/paddle_tpu_profile"):
    global _active_dir
    _active_dir = log_dir
    _tracer.clear()
    _tracer.enabled = True
    jax.profiler.start_trace(log_dir)


def stop_profiler(sorted_key="total", profile_path=None):
    """Stop tracing; optionally write a chrome://tracing JSON of host spans
    (reference: tools/timeline.py output) and print the summary table.
    Returns the collected spans as (name, t0_s, dur_s) tuples."""
    global _active_dir
    if _active_dir is None:
        return
    jax.profiler.stop_trace()
    _active_dir = None
    _tracer.enabled = False
    span_events = [ev for ev in _tracer.events() if ev.ph == "X"]
    events = [(ev.name, ev.ts / 1e6, ev.dur / 1e6)
              for ev in span_events]
    if profile_path:
        # bare event list (no process/thread metadata): the reference
        # converter emitted exactly one JSON object per recorded span
        trace = _tracing.to_chrome_trace(span_events)
        os.makedirs(os.path.dirname(os.path.abspath(profile_path)),
                    exist_ok=True)
        with open(profile_path, "w") as f:
            json.dump(trace, f)
    if events:
        agg = {}
        for name, _, dur in events:
            tot, cnt, mx, mn = agg.get(name,
                                       (0.0, 0, -float("inf"),
                                        float("inf")))
            agg[name] = (tot + dur, cnt + 1, max(mx, dur), min(mn, dur))
        # max/min sort by the per-event extreme DURATION (reference
        # summary semantics: EventSortingKey::kMin also sorts
        # DESCENDING, like every other key), not by total time
        sort_fns = {"total": lambda kv: -kv[1][0],
                    "calls": lambda kv: -kv[1][1],
                    "ave": lambda kv: -(kv[1][0] / kv[1][1]),
                    "max": lambda kv: -kv[1][2],
                    "min": lambda kv: -kv[1][3]}
        rows = sorted(agg.items(),
                      key=sort_fns.get(sorted_key or "total",
                                       sort_fns["total"]))
        print(f"{'Event':<40} {'Calls':>8} {'Total(ms)':>12} "
              f"{'Avg(ms)':>12} {'Max(ms)':>12} {'Min(ms)':>12}")
        for name, (tot, cnt, mx, mn) in rows:
            print(f"{name:<40} {cnt:>8} {tot * 1e3:>12.3f} "
                  f"{tot / cnt * 1e3:>12.3f} {mx * 1e3:>12.3f} "
                  f"{mn * 1e3:>12.3f}")
    return events


@contextlib.contextmanager
def profiler(state="All", tracer_option="Default",
             log_dir="/tmp/paddle_tpu_profile", profile_path=None):
    start_profiler(state, tracer_option, log_dir)
    try:
        yield
    finally:
        stop_profiler(profile_path=profile_path)


class Timer:
    def __init__(self):
        self.reset()

    def reset(self):
        self._start = None
        self.total = 0.0
        self.count = 0

    def start(self):
        self._start = time.perf_counter()

    def stop(self):
        self.total += time.perf_counter() - self._start
        self.count += 1

    def mean(self):
        return self.total / max(self.count, 1)


def reset_profiler():
    """reference: fluid/profiler.py reset_profiler — drop collected
    host events.  The tracer clears its ring buffers under their lock:
    concurrent RecordEvent.__exit__ appends race an unlocked clear()."""
    _tracer.clear()


class cuda_profiler:
    """reference: fluid/profiler.py cuda_profiler — CUDA-specific nvprof
    control; a no-op context on TPU (jax.profiler covers device traces)."""

    def __init__(self, *a, **k):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
