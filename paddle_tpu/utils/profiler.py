"""Profiler.

Reference parity: host RecordEvent spans + CUPTI device tracer + chrome
trace export (``platform/profiler.cc:196``, ``device_tracer.cc:57``,
``tools/timeline.py``).  TPU-native: ``jax.profiler`` emits an XPlane trace
(TensorBoard / Perfetto-compatible — the chrome://tracing successor);
RecordEvent maps to ``jax.profiler.TraceAnnotation`` so host spans correlate
with device activity in the same trace.  Host spans are additionally
collected in-process so ``stop_profiler(profile_path=...)`` can write a
standalone chrome://tracing JSON and print the reference-style summary
table (sorted by total time) without TensorBoard.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time

import jax

_host_events = []        # (name, t0, dur) while profiling is active
_collecting = False
_lock = threading.Lock()


class RecordEvent:
    """RAII span (reference: platform/profiler.h RecordEvent)."""

    def __init__(self, name):
        self.name = name
        self._ann = None

    def __enter__(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._t0
        if _collecting:
            with _lock:
                _host_events.append((self.name, self._t0, self.elapsed))
        self._ann.__exit__(*exc)
        return False


_active_dir = None


def start_profiler(state="All", tracer_option="Default",
                   log_dir="/tmp/paddle_tpu_profile"):
    global _active_dir, _collecting
    _active_dir = log_dir
    with _lock:
        _host_events.clear()
    _collecting = True
    jax.profiler.start_trace(log_dir)


def stop_profiler(sorted_key="total", profile_path=None):
    """Stop tracing; optionally write a chrome://tracing JSON of host spans
    (reference: tools/timeline.py output) and print the summary table."""
    global _active_dir, _collecting
    if _active_dir is None:
        return
    jax.profiler.stop_trace()
    _active_dir = None
    _collecting = False
    with _lock:
        events = list(_host_events)
    if profile_path:
        trace = {"traceEvents": [
            {"name": name, "ph": "X", "pid": 0, "tid": 0,
             "ts": t0 * 1e6, "dur": dur * 1e6, "cat": "host"}
            for name, t0, dur in events]}
        os.makedirs(os.path.dirname(os.path.abspath(profile_path)),
                    exist_ok=True)
        with open(profile_path, "w") as f:
            json.dump(trace, f)
    if events:
        agg = {}
        for name, _, dur in events:
            tot, cnt, mx, mn = agg.get(name,
                                       (0.0, 0, -float("inf"),
                                        float("inf")))
            agg[name] = (tot + dur, cnt + 1, max(mx, dur), min(mn, dur))
        # max/min sort by the per-event extreme DURATION (reference
        # summary semantics: EventSortingKey::kMin also sorts
        # DESCENDING, like every other key), not by total time
        sort_fns = {"total": lambda kv: -kv[1][0],
                    "calls": lambda kv: -kv[1][1],
                    "ave": lambda kv: -(kv[1][0] / kv[1][1]),
                    "max": lambda kv: -kv[1][2],
                    "min": lambda kv: -kv[1][3]}
        rows = sorted(agg.items(),
                      key=sort_fns.get(sorted_key or "total",
                                       sort_fns["total"]))
        print(f"{'Event':<40} {'Calls':>8} {'Total(ms)':>12} "
              f"{'Avg(ms)':>12} {'Max(ms)':>12} {'Min(ms)':>12}")
        for name, (tot, cnt, mx, mn) in rows:
            print(f"{name:<40} {cnt:>8} {tot * 1e3:>12.3f} "
                  f"{tot / cnt * 1e3:>12.3f} {mx * 1e3:>12.3f} "
                  f"{mn * 1e3:>12.3f}")
    return events


@contextlib.contextmanager
def profiler(state="All", tracer_option="Default",
             log_dir="/tmp/paddle_tpu_profile", profile_path=None):
    start_profiler(state, tracer_option, log_dir)
    try:
        yield
    finally:
        stop_profiler(profile_path=profile_path)


class Timer:
    def __init__(self):
        self.reset()

    def reset(self):
        self._start = None
        self.total = 0.0
        self.count = 0

    def start(self):
        self._start = time.perf_counter()

    def stop(self):
        self.total += time.perf_counter() - self._start
        self.count += 1

    def mean(self):
        return self.total / max(self.count, 1)


def reset_profiler():
    """reference: fluid/profiler.py reset_profiler — drop collected
    host events.  Takes the lock: concurrent RecordEvent.__exit__
    appends race an unlocked clear()."""
    with _lock:
        _host_events.clear()


class cuda_profiler:
    """reference: fluid/profiler.py cuda_profiler — CUDA-specific nvprof
    control; a no-op context on TPU (jax.profiler covers device traces)."""

    def __init__(self, *a, **k):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
