"""Profiler.

Reference parity: host RecordEvent spans + CUPTI device tracer + chrome
trace export (``platform/profiler.cc:196``, ``device_tracer.cc:57``,
``tools/timeline.py``).  TPU-native: ``jax.profiler`` emits an XPlane trace
(TensorBoard / Perfetto-compatible — the chrome://tracing successor);
RecordEvent maps to ``jax.profiler.TraceAnnotation`` so host spans correlate
with device activity in the same trace.
"""
from __future__ import annotations

import contextlib
import time

import jax


class RecordEvent:
    """RAII span (reference: platform/profiler.h RecordEvent)."""

    def __init__(self, name):
        self.name = name
        self._ann = None

    def __enter__(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._t0
        self._ann.__exit__(*exc)
        return False


_active_dir = None


def start_profiler(state="All", tracer_option="Default",
                   log_dir="/tmp/paddle_tpu_profile"):
    global _active_dir
    _active_dir = log_dir
    jax.profiler.start_trace(log_dir)


def stop_profiler(sorted_key=None, profile_path=None):
    global _active_dir
    if _active_dir is not None:
        jax.profiler.stop_trace()
        _active_dir = None


@contextlib.contextmanager
def profiler(state="All", tracer_option="Default",
             log_dir="/tmp/paddle_tpu_profile"):
    start_profiler(state, tracer_option, log_dir)
    try:
        yield
    finally:
        stop_profiler()


class Timer:
    def __init__(self):
        self.reset()

    def reset(self):
        self._start = None
        self.total = 0.0
        self.count = 0

    def start(self):
        self._start = time.perf_counter()

    def stop(self):
        self.total += time.perf_counter() - self._start
        self.count += 1

    def mean(self):
        return self.total / max(self.count, 1)
