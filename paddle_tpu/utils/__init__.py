"""Utilities: profiler spans, timers, download shim, unique_name."""
from . import profiler  # noqa: F401
from . import unique_name  # noqa: F401


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError:
        return None


def run_check():
    """paddle.utils.run_check parity — quick health check of the stack."""
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    x = Tensor(jnp.ones((2, 2)))
    y = (x @ x).numpy()
    n = len(jax.devices())
    print(f"paddle_tpu is installed successfully! "
          f"{n} device(s): {jax.devices()[0].platform}")
    return True
from . import dlpack  # noqa: E402,F401


def deprecated(update_to="", since="", reason=""):
    """reference: utils/deprecated.py — decorator emitting a
    DeprecationWarning on first call."""
    import functools
    import warnings

    def decorator(fn):
        msg = f"API '{fn.__module__}.{fn.__name__}' is deprecated"
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f", use '{update_to}' instead"
        if reason:
            msg += f". Reason: {reason}"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return decorator


def require_version(min_version, max_version=None):
    """reference: utils/install_check-adjacent require_version — compare
    against this package's version."""
    from ..version import full_version

    def parse(v):
        return [int(x) for x in str(v).split(".")[:3] if x.isdigit()]

    cur = parse(full_version)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {full_version} < required minimum "
            f"{min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {full_version} > required maximum "
            f"{max_version}")
    return True


def dump_config(config=None):
    """reference: print build/config info."""
    import jax
    from ..version import full_version
    print(f"paddle_tpu {full_version}; jax {jax.__version__}; "
          f"backend {jax.default_backend()}")


def load_op_library(path):
    raise NotImplementedError(
        "load_op_library loads CUDA custom-op .so files; on this backend "
        "write custom ops as jax.custom_vjp functions or Pallas kernels "
        "(see nn/functional/attention.py for the pattern)")


# download module (reference: utils/download.py). No network egress in
# this environment: resolves cache hits, errors actionably on misses.
import sys as _sys
import types as _types

download = _types.ModuleType(__name__ + ".download")


def _get_weights_path_from_url(url, md5sum=None):
    import os
    cache = os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                         "weights")
    fname = os.path.join(cache, url.split("/")[-1])
    if os.path.exists(fname):
        return fname
    raise RuntimeError(
        f"weights for {url} not in cache ({cache}) and this environment "
        "has no network egress — place the file there manually")


download.get_weights_path_from_url = _get_weights_path_from_url
download.get_path_from_url = _get_weights_path_from_url
_sys.modules[download.__name__] = download

# profiler class aliases (reference: utils/profiler.py Profiler API)
Profiler = profiler.Profiler if hasattr(profiler, "Profiler") else None
ProfilerOptions = getattr(profiler, "ProfilerOptions", None)
get_profiler = getattr(profiler, "get_profiler", None)
