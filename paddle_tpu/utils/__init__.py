"""Utilities: profiler spans, timers, download shim, unique_name."""
from . import profiler  # noqa: F401
from . import unique_name  # noqa: F401


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError:
        return None


def run_check():
    """paddle.utils.run_check parity — quick health check of the stack."""
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    x = Tensor(jnp.ones((2, 2)))
    y = (x @ x).numpy()
    n = len(jax.devices())
    print(f"paddle_tpu is installed successfully! "
          f"{n} device(s): {jax.devices()[0].platform}")
    return True
from . import dlpack  # noqa: E402,F401
