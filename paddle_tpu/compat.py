"""paddle.compat (reference: python/paddle/compat.py) — py2/3 text utils
kept for API parity."""
from __future__ import annotations


def to_text(obj, encoding="utf-8", inplace=False):
    if isinstance(obj, bytes):
        return obj.decode(encoding)
    if isinstance(obj, (list, set, tuple)):
        return type(obj)(to_text(o, encoding) for o in obj)
    return obj


def to_bytes(obj, encoding="utf-8", inplace=False):
    if isinstance(obj, str):
        return obj.encode(encoding)
    if isinstance(obj, (list, set, tuple)):
        return type(obj)(to_bytes(o, encoding) for o in obj)
    return obj


def round(x, d=0):
    import builtins
    return float(builtins.round(x, d))


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    return str(exc)
