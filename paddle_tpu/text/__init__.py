"""paddle.text parity surface (reference: python/paddle/text/ — datasets
only in this snapshot: Imdb, Imikolov, Conll05st, MovieLens, UCIHousing,
WMT14, WMT16)."""
from .datasets import (  # noqa: F401
    Imdb, Imikolov, Conll05st, Movielens, UCIHousing, WMT14, WMT16,
)

__all__ = ["Imdb", "Imikolov", "Conll05st", "Movielens", "UCIHousing",
           "WMT14", "WMT16", "viterbi_decode", "ViterbiDecoder"]

from ..nn.functional.extension import viterbi_decode  # noqa: E402,F401


class ViterbiDecoder:
    """paddle.text.ViterbiDecoder — stateful wrapper over viterbi_decode."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)

# package-style submodule aliases (reference text/datasets/ has one module
# per dataset)
import sys as _sys
import types as _types
from . import datasets as _d


def _alias(name, **attrs):
    m = _types.ModuleType(f"{__name__}.datasets.{name}")
    for k, v in attrs.items():
        setattr(m, k, v)
    _sys.modules[m.__name__] = m
    setattr(_d, name, m)
    return m


_alias("imdb", Imdb=_d.Imdb)
_alias("imikolov", Imikolov=_d.Imikolov)
_alias("conll05", Conll05st=_d.Conll05st)
_alias("movielens", Movielens=_d.Movielens)
_alias("uci_housing", UCIHousing=_d.UCIHousing)
_alias("wmt14", WMT14=_d.WMT14)
_alias("wmt16", WMT16=_d.WMT16)
