"""paddle.text parity surface (reference: python/paddle/text/ — datasets
only in this snapshot: Imdb, Imikolov, Conll05st, MovieLens, UCIHousing,
WMT14, WMT16)."""
from .datasets import (  # noqa: F401
    Imdb, Imikolov, Conll05st, Movielens, UCIHousing, WMT14, WMT16,
)

__all__ = ["Imdb", "Imikolov", "Conll05st", "Movielens", "UCIHousing",
           "WMT14", "WMT16", "viterbi_decode", "ViterbiDecoder"]

from ..nn.functional.extension import viterbi_decode  # noqa: E402,F401


class ViterbiDecoder:
    """paddle.text.ViterbiDecoder — stateful wrapper over viterbi_decode."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
