"""paddle.text parity surface (reference: python/paddle/text/ — datasets
only in this snapshot: Imdb, Imikolov, Conll05st, MovieLens, UCIHousing,
WMT14, WMT16)."""
from .datasets import (  # noqa: F401
    Imdb, Imikolov, Conll05st, Movielens, UCIHousing, WMT14, WMT16,
)

__all__ = ["Imdb", "Imikolov", "Conll05st", "Movielens", "UCIHousing",
           "WMT14", "WMT16"]
