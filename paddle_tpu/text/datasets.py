"""Text datasets.

Reference parity: ``python/paddle/text/datasets/*`` (Imdb imdb.py:139,
Imikolov imikolov.py:166, Conll05st, Movielens, UCIHousing, WMT14
wmt14.py:166, WMT16).  Item tuples keep the reference's exact shapes/dtypes.

TPU-host note: no egress in this environment — each dataset loads a local
cache file when present and otherwise produces a deterministic synthetic
corpus with the reference's vocabulary sizes and item structure, so data
pipelines and models remain testable offline (same policy as
vision/datasets.py).  Size is controlled by PADDLE_TPU_SYNTH_N.
"""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset

from ..dataset.common import data_home as _data_home

DATA_HOME = _data_home()  # snapshot for back-compat importers


def _synth_n(default=512):
    return int(os.environ.get("PADDLE_TPU_SYNTH_N", default))


def _zipf_doc(rs, vocab, lo=10, hi=60):
    n = rs.randint(lo, hi)
    # zipfian-ish ids: frequent small ids like real text
    return (rs.zipf(1.3, n) % vocab).astype(np.int64)


class Imdb(Dataset):
    """Sentiment docs: (word_ids [L], label [1]) — imdb.py:139."""

    VOCAB = 5147  # reference build_dict cutoff ~150 -> ~5k words

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        assert mode in ("train", "test")
        self.mode = mode
        rs = np.random.RandomState(42 if mode == "train" else 43)
        n = _synth_n()
        self.docs = [_zipf_doc(rs, self.VOCAB) for _ in range(n)]
        self.labels = rs.randint(0, 2, n).astype(np.int64)
        # synthetic signal: positive docs skew towards even token ids
        for i, lab in enumerate(self.labels):
            if lab == 1:
                self.docs[i] = (self.docs[i] // 2 * 2) % self.VOCAB
        self.word_idx = {i: i for i in range(self.VOCAB)}

    def __getitem__(self, idx):
        return np.array(self.docs[idx]), np.array([self.labels[idx]])

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-grams: tuple of n word-id arrays — imikolov.py:166."""

    VOCAB = 2074

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        assert data_type in ("NGRAM", "SEQ")
        self.data_type = data_type
        self.window_size = window_size
        rs = np.random.RandomState(7 if mode == "train" else 8)
        n = _synth_n()
        self.data = []
        for _ in range(n):
            if data_type == "NGRAM":
                gram = (rs.zipf(1.3, window_size) % self.VOCAB).astype(
                    np.int64)
                self.data.append(tuple(np.array(g) for g in gram))
            else:
                seq = _zipf_doc(rs, self.VOCAB)
                self.data.append((seq[:-1], seq[1:]))
        self.word_idx = {i: i for i in range(self.VOCAB)}

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


class Conll05st(Dataset):
    """SRL tuples: (pred_idx, mark, word_ids..., label_ids) per the
    reference conll05.py 9-field record."""

    WORD_DICT = 44068
    LABEL_DICT = 59
    PRED_DICT = 3162

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="train",
                 download=True):
        rs = np.random.RandomState(11 if mode == "train" else 12)
        n = _synth_n(256)
        self.examples = []
        for _ in range(n):
            L = rs.randint(5, 30)
            words = (rs.zipf(1.3, L) % self.WORD_DICT).astype(np.int64)
            ctx = [(words + k) % self.WORD_DICT for k in range(5)]
            pred = np.full(L, rs.randint(0, self.PRED_DICT), np.int64)
            mark = (rs.rand(L) < 0.2).astype(np.int64)
            labels = (rs.zipf(1.5, L) % self.LABEL_DICT).astype(np.int64)
            self.examples.append((words, *ctx, pred, mark, labels))

    def get_dict(self):
        return ({i: i for i in range(self.WORD_DICT)},
                {i: i for i in range(self.PRED_DICT)},
                {i: i for i in range(self.LABEL_DICT)})

    def __getitem__(self, idx):
        return self.examples[idx]

    def __len__(self):
        return len(self.examples)


class Movielens(Dataset):
    """Rating rows: (user_id, gender, age, job, movie_id, title_ids,
    categories, rating) per reference movielens.py."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        rs = np.random.RandomState(rand_seed + (0 if mode == "train"
                                                else 1))
        n = _synth_n()
        self.rows = []
        for _ in range(n):
            self.rows.append((
                np.array([rs.randint(1, 6041)], np.int64),
                np.array([rs.randint(0, 2)], np.int64),
                np.array([rs.randint(0, 7)], np.int64),
                np.array([rs.randint(0, 21)], np.int64),
                np.array([rs.randint(1, 3953)], np.int64),
                (rs.zipf(1.3, 4) % 5175).astype(np.int64),
                (rs.zipf(1.3, 2) % 19).astype(np.int64),
                np.array([float(rs.randint(1, 6))], np.float32),
            ))

    def __getitem__(self, idx):
        return self.rows[idx]

    def __len__(self):
        return len(self.rows)


class UCIHousing(Dataset):
    """Regression rows: (feature [13] f32, price [1] f32) — uci_housing.py.
    Loads the real housing.data when cached locally, else synthesizes a
    linear-plus-noise problem (so regression converges in tests)."""

    def __init__(self, data_file=None, mode="train", download=True):
        assert mode in ("train", "test")
        path = data_file or os.path.join(_data_home(), "uci_housing",
                                         "housing.data")
        if os.path.exists(path):
            raw = np.loadtxt(path).astype(np.float32)
        else:
            rs = np.random.RandomState(5)
            n = _synth_n()
            feats = rs.rand(n, 13).astype(np.float32)
            w = rs.randn(13).astype(np.float32)
            prices = feats @ w + 0.1 * rs.randn(n).astype(np.float32)
            raw = np.concatenate([feats, prices[:, None]], axis=1)
        # reference normalization: feature-wise max/min scaling
        feats = raw[:, :-1]
        mx, mn, avg = feats.max(0), feats.min(0), feats.mean(0)
        denom = np.where(mx - mn == 0, 1, mx - mn)
        feats = (feats - avg) / denom
        raw = np.concatenate([feats, raw[:, -1:]], axis=1)
        split = int(len(raw) * 0.8)
        self.data = raw[:split] if mode == "train" else raw[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1].astype(np.float32), row[-1:].astype(np.float32)

    def __len__(self):
        return len(self.data)


class _WMTBase(Dataset):
    SRC_VOCAB = 30000
    TRG_VOCAB = 30000
    START, END, UNK = 0, 1, 2

    def __init__(self, mode="train", seed=21):
        rs = np.random.RandomState(seed + (0 if mode == "train" else 1))
        n = _synth_n(256)
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        for _ in range(n):
            src = _zipf_doc(rs, self.SRC_VOCAB, 4, 30)
            trg_core = _zipf_doc(rs, self.TRG_VOCAB, 4, 30)
            trg = np.concatenate([[self.START], trg_core])
            trg_next = np.concatenate([trg_core, [self.END]])
            self.src_ids.append(src)
            self.trg_ids.append(trg.astype(np.int64))
            self.trg_ids_next.append(trg_next.astype(np.int64))

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)


class WMT14(_WMTBase):
    """EN→FR ids triple — wmt14.py:166."""

    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 download=True):
        self.SRC_VOCAB = self.TRG_VOCAB = dict_size
        super().__init__(mode=mode, seed=21)


class WMT16(_WMTBase):
    """EN→DE ids triple — wmt16.py."""

    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", download=True):
        self.SRC_VOCAB = src_dict_size
        self.TRG_VOCAB = trg_dict_size
        super().__init__(mode=mode, seed=23)
