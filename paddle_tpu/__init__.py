"""paddle_tpu — a TPU-native deep learning framework with the capabilities
of PaddlePaddle (~v2.0), built on JAX/XLA/pjit/Pallas.

Blueprint: /root/repo/SURVEY.md (structural analysis of the reference).
The public API mirrors ``python/paddle`` where that API is device-neutral;
everything CUDA-shaped in the reference (streams, places, NCCL rings, kernel
registries) is replaced by XLA compilation over device meshes.
"""
from __future__ import annotations

import os as _os

# Honor an explicit platform selection BEFORE any jax backend init.  The
# axon TPU plugin ignores the JAX_PLATFORMS env var, so subprocesses
# (examples, CI, DataLoader-adjacent tools) that must stay off the TPU —
# e.g. while another process holds the chip — set PADDLE_TPU_PLATFORM=cpu
# and this config (which axon does respect) applies it.
if _os.environ.get("PADDLE_TPU_PLATFORM"):
    import jax as _jax

    _jax.config.update("jax_platforms",
                       _os.environ["PADDLE_TPU_PLATFORM"])

__version__ = "2.0.0-tpu"  # tracks the reference's 2.0 API surface

# -- core ----------------------------------------------------------------
from .core.tensor import Tensor, Parameter, to_tensor  # noqa: F401
from .core.autograd import no_grad, enable_grad  # noqa: F401
from .core import autograd as _autograd
from .core.device import (  # noqa: F401
    set_device, get_device, device_count, CPUPlace, TPUPlace,
    is_compiled_with_cuda, is_compiled_with_xpu, is_compiled_with_tpu,
)
from .core.dtype import (  # noqa: F401
    set_default_dtype, get_default_dtype,
    bool_ as bool8, uint8, int8, int16, int32, int64,
    float16, bfloat16, float32, float64, complex64, complex128,
)
from .core.flags import set_flags, get_flags  # noqa: F401
from .core.rng import seed  # noqa: F401
from .core import rng as _rng

# -- ops (also attaches Tensor methods) ----------------------------------
from .ops import *  # noqa: F401,F403
from .ops import linalg  # noqa: F401
from . import ops  # noqa: F401


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """paddle.grad — gradients of outputs wrt inputs via the eager tape.

    Implemented by running backward with retain_graph and reading the leaf
    grads.  ``create_graph=True`` records the backward itself on the tape
    (reference: imperative/partial_grad_engine.cc), so the returned grads
    are differentiable — gradient-penalty / double-grad training works.
    """
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if retain_graph is None:
        retain_graph = create_graph
    saved = [(t.grad, t._retain_grad) for t in inputs]
    for t in inputs:
        t.grad = None
        t._retain_grad = True
    _autograd.backward(list(outputs), grad_outputs,
                       retain_graph=bool(retain_graph),
                       create_graph=create_graph,
                       _leaf_targets={id(t) for t in inputs})
    grads = []
    for t, (old, old_retain) in zip(inputs, saved):
        g = t.grad
        if g is None and not allow_unused:
            g = ops.zeros_like(t)
        grads.append(g)
        t.grad = old
        t._retain_grad = old_retain
    return grads


# -- subsystems ----------------------------------------------------------
from . import nn  # noqa: E402,F401
from . import models  # noqa: E402,F401
from . import optimizer  # noqa: E402,F401
from . import amp  # noqa: E402,F401
from . import io  # noqa: E402,F401
from . import jit  # noqa: E402,F401
from . import distributed  # noqa: E402,F401
from . import metric  # noqa: E402,F401
from . import vision  # noqa: E402,F401
from . import hapi  # noqa: E402,F401
from . import static  # noqa: E402,F401
from . import distribution  # noqa: E402,F401
from . import text  # noqa: E402,F401
from . import inference  # noqa: E402,F401
from . import utils  # noqa: E402,F401
from . import monitor  # noqa: E402,F401
from . import serving  # noqa: E402,F401
from .framework.io import save, load  # noqa: E402,F401
from .static import (enable_static, disable_static,  # noqa: E402,F401
                     in_dynamic_mode)
from .ops.manipulation import flip as reverse  # noqa: E402,F401
from .static.program import in_static_mode  # noqa: E402,F401

# ---- 1.x-compat aliases & auxiliary modules (reference __init__.py
# DEFINE_ALIAS block + module imports) ------------------------------------
from .ops.compat_ops import (  # noqa: E402,F401
    add_n, kron, broadcast_shape, rank, shape, is_tensor, is_empty,
    unstack, slice, strided_slice, crop_tensor, crop_tensor as crop,
    fill_constant,
    create_global_var, create_parameter, has_inf, has_nan,
    elementwise_add, elementwise_sub, elementwise_mul, elementwise_div,
    elementwise_pow, elementwise_mod, elementwise_floordiv,
    elementwise_max, elementwise_min,
    reduce_sum, reduce_mean, reduce_max, reduce_min, reduce_prod,
    tanh_, squeeze_, unsqueeze_, scatter_, exp_, sqrt_, ceil_, floor_,
    round_, clip_, subtract_, add_, set_printoptions,
    create_array, array_write, array_read, array_length)
from .ops.linalg import (cholesky, cross, dist, histogram,  # noqa: E402,F401
                         inverse, norm, bincount)
from . import device  # noqa: E402,F401
from . import regularizer  # noqa: E402,F401
from . import compat  # noqa: E402,F401
from . import sysconfig  # noqa: E402,F401
from . import onnx  # noqa: E402,F401
from . import incubate  # noqa: E402,F401
from . import version  # noqa: E402,F401
from .batch import batch  # noqa: E402,F401
from . import reader  # noqa: E402,F401
from .nn.param_attr import ParamAttr  # noqa: E402,F401
from .core.tensor import Tensor as VarBase  # noqa: E402,F401
from .core.tensor import Tensor as LoDTensor  # noqa: E402,F401
from .hapi import callbacks  # noqa: E402,F401
from . import ops as tensor  # noqa: E402,F401  (paddle.tensor alias)
from .static import data  # noqa: E402,F401

LoDTensorArray = list  # reference: vector<LoDTensor> bound to a list

full_version = __version__
commit = "tpu-native"


def get_tensor_from_selected_rows(x, name=None):
    """Densify a SelectedRows gradient (reference:
    get_tensor_from_selected_rows_op.cc).  Eager ``nn.Embedding(...,
    sparse=True)`` grads are ``core.selected_rows.SelectedRows``; this
    returns their scatter-added dense form.  Dense tensors pass through."""
    from .core.selected_rows import SelectedRows
    if isinstance(x, SelectedRows):
        return Tensor(x._data, stop_gradient=True)
    return x


def in_dygraph_mode():
    return in_dynamic_mode()


def enable_dygraph(place=None):
    disable_static()


def disable_dygraph():
    enable_static()


class CUDAPlace:
    """Accepted for API compat; placement is XLA's job on TPU."""

    def __init__(self, dev_id=0):
        self.dev_id = dev_id


class CUDAPinnedPlace:
    pass


class XPUPlace:
    def __init__(self, dev_id=0):
        self.dev_id = dev_id


def get_cudnn_version():
    return None  # no cuDNN on TPU


def get_cuda_rng_state():
    from .core import rng as _rng
    return [_rng.get_seed()]


def set_cuda_rng_state(state):
    from .core import rng as _rng
    if state:
        _rng.seed(int(state[0]))


def monkey_patch_variable():
    pass  # operators are attached at import time (ops/__init__.py)


def monkey_patch_math_varbase():
    pass


def summary(net, input_size=None, dtypes=None):
    """paddle.summary (reference: hapi/model_summary.py)."""
    from .hapi.model import Model
    return Model(net).summary(input_size, dtype=dtypes)


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Rough FLOPs count: 2*params per MAC-dominated layer (reference:
    hapi/dynamic_flops.py walks per-layer hooks; here dense/conv params
    dominate on the MXU)."""
    import numpy as _np
    total = 0
    for _, p in net.named_parameters():
        n = int(_np.prod(p.shape))
        if len(p.shape) >= 2:
            total += 2 * n
    return total
from .hapi.model import Model  # noqa: E402,F401
from .nn.layer.base import Layer  # noqa: E402,F401
from . import framework  # noqa: E402,F401
from .framework import random  # noqa: E402,F401

DataParallel = None  # set by paddle_tpu.distributed at import


def _late_bind():
    global DataParallel
    from .distributed.parallel import DataParallel as _DP
    DataParallel = _DP


_late_bind()
del _late_bind


# fluid namespace last: it re-exports names defined above (places, etc.)
from . import fluid  # noqa: E402,F401
from . import dataset  # noqa: E402,F401  (1.x reader factories)
from . import quantization  # noqa: E402,F401
