"""Eager op dispatcher.

Reference parity: this is the TPU-native replacement for the whole kernel
machinery — op registry (``framework/op_registry.h:256``), kernel dispatch
(``framework/operator.cc:1068,1203``), eager trace
(``imperative/tracer.cc:132``) and generated fast entry points
(``pybind/op_function_generator.cc:488``).

Design: an "op" is a pure function over jax arrays (+ static kwargs).
``primitive`` wraps it so that, called with Tensors:
  1. arrays are unwrapped, AMP may recast them (amp hook),
  2. if autograd is on and any floating input requires grad, the forward runs
     under ``jax.vjp`` and the resulting closure is recorded on the tape,
  3. outputs are wrapped back into Tensors.
There is exactly one "kernel" per op — XLA lowers it to every backend — so
the reference's (place, dtype, layout, library) kernel-key machinery has no
analogue here by construction.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import autograd
from .flags import flag
from .tensor import Tensor
from . import dtype as dtypes

# set by paddle_tpu.amp at import; fn(op_name, arrays) -> arrays
amp_input_hook = None

# set by paddle_tpu.static at import; fn(op_name, raw_fn, args, kwargs,
# has_aux) -> recorded Variables, or NotImplemented to run eagerly.  This is
# the single switch between the two execution modes the reference needed two
# runtimes for (imperative/tracer.cc vs framework/executor.cc).
static_record_hook = None


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _is_diff_tensor(x):
    return (isinstance(x, Tensor) and not x.stop_gradient
            and jnp.issubdtype(x._data.dtype, jnp.floating))


def _check_nan(name, arrays):
    for a in arrays:
        if isinstance(a, jax.core.Tracer):
            return
        if jnp.issubdtype(a.dtype, jnp.floating):
            if bool(jnp.any(~jnp.isfinite(a))):
                raise FloatingPointError(
                    f"NaN/Inf detected in output of op '{name}' "
                    f"(FLAGS_check_nan_inf) — reference parity: "
                    f"framework/details/nan_inf_utils_detail.cc:293")


def primitive(name=None, nondiff=(), has_aux=False):
    """Wrap a pure jax function into an eager, tape-aware op.

    nondiff: positional indices never differentiated.
    has_aux: fn returns (diff_out, aux_out); aux gets no gradient (used by
             topk/max-with-index style ops).
    """

    def deco(fn):
        op_name = name or fn.__name__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if static_record_hook is not None:
                rec = static_record_hook(op_name, fn, args, kwargs, has_aux)
                if rec is not NotImplemented:
                    return rec
            arrays = [_unwrap(a) for a in args]
            if amp_input_hook is not None:
                arrays = amp_input_hook(op_name, arrays)

            diff_idx = [
                i for i, a in enumerate(args)
                if i not in nondiff and _is_diff_tensor(a)
            ] if autograd.grad_enabled() else []

            if not diff_idx:
                out = fn(*arrays, **kwargs)
                if has_aux:
                    out, aux = out
                    res = _wrap_out(op_name, out, True) + _wrap_out(
                        op_name, aux, True)
                    return tuple(res) if len(res) > 1 else res[0]
                res = _wrap_out(op_name, out, True)
                return tuple(res) if len(res) > 1 else res[0]

            def closed(*diff_arrays):
                full = list(arrays)
                for i, d in zip(diff_idx, diff_arrays):
                    full[i] = d
                return fn(*full, **kwargs)

            primal_in = tuple(arrays[i] for i in diff_idx)
            if has_aux:
                out, vjp_fn, aux = jax.vjp(closed, *primal_in, has_aux=True)
            else:
                out, vjp_fn, aux = *jax.vjp(closed, *primal_in), None

            out_tensors = _wrap_out(op_name, out, False)
            node = autograd.record([args[i] for i in diff_idx], out_tensors,
                                   _structured_vjp(vjp_fn, out), op_name)
            node.primal_fn = closed
            node.primal_in = primal_in
            node.out_container = type(out) if isinstance(
                out, (tuple, list)) else None
            node.primal_has_aux = has_aux
            res = list(out_tensors)
            if aux is not None:
                res += _wrap_out(op_name, aux, True)
            return tuple(res) if len(res) > 1 else res[0]

        wrapper.op_name = op_name
        wrapper.raw_fn = fn
        return wrapper

    return deco


def _structured_vjp(vjp_fn, out):
    """Adapt tape cotangent convention (tuple of arrays) to vjp pytree."""
    if isinstance(out, (tuple, list)):
        def run(ct):
            return vjp_fn(type(out)(ct) if isinstance(ct, tuple) else (ct,))
        return run

    def run_single(ct):
        return vjp_fn(ct)
    return run_single


def _wrap_out(name, out, stop_gradient):
    outs = out if isinstance(out, (tuple, list)) else (out,)
    if flag("check_nan_inf"):
        _check_nan(name, [o for o in outs if hasattr(o, "dtype")])
    return [Tensor(o, stop_gradient=stop_gradient) for o in outs]


def ensure_tensor(x, dtype=None, ref=None):
    """Coerce python scalars / numpy / Tensor into Tensor (broadcast helper)."""
    if isinstance(x, Tensor):
        return x
    if (ref is not None and isinstance(ref, Tensor) and dtype is None
            and isinstance(x, (int, float, bool))):
        # scalar operand adopts the tensor operand's dtype (paddle semantics)
        return Tensor(jnp.asarray(x, _unwrap(ref).dtype))
    return Tensor(x, dtype=dtype)
