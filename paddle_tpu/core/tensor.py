"""Eager Tensor.

Reference parity: dygraph ``VarBase`` (``paddle/fluid/imperative/layer.h``,
pybind surface ``pybind/imperative.cc``) + ``framework::Tensor``
(``paddle/fluid/framework/tensor.h:89``).

TPU-native design: a thin mutable handle around an immutable ``jax.Array``.
There is no allocator / Place zoo — XLA owns HBM; "mutation" (set_value,
optimizer updates, in-place ops) swaps the underlying array.  The same Tensor
object flows through eager ops and through jit traces (where ``_data`` is a
tracer), which is what lets one Layer codebase serve both execution modes
(the reference needed two runtimes for this — imperative/ + framework/).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtypes
from . import device as device_mod
from . import autograd

Value = object  # jax.Array | tracer


class Tensor:
    _next_id = [0]

    __slots__ = ("_data", "_stop_gradient", "grad", "_grad_node",
                 "_retain_grad", "name", "persistable", "__weakref__",
                 "__dict__")

    def __init__(self, data, dtype=None, place=None, stop_gradient=True,
                 name=None):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, jax.Array) and not isinstance(
                data, jax.core.Tracer):
            data = np.asarray(data)
            if dtype is None and data.dtype == np.float64:
                # numpy literals default to f64; paddle defaults to f32
                data = data.astype(dtypes.to_jax(dtypes.get_default_dtype()))
            dev = device_mod.jax_device(place)
            data = jnp.asarray(
                data, dtypes.to_jax(dtype) if dtype else None)
            if isinstance(data, jax.Array):
                data = jax.device_put(data, dev)
        elif dtype is not None:
            data = data.astype(dtypes.to_jax(dtype))
        self._data = data
        self._stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._retain_grad = False
        Tensor._next_id[0] += 1
        self.name = name or f"tensor_{Tensor._next_id[0]}"
        self.persistable = False

    # -- metadata ---------------------------------------------------------
    # paddle semantics: `trainable` is the inverse alias of `stop_gradient`
    # (fluid Parameter keeps them in sync); one backing slot avoids the two
    # flags drifting apart when users flip stop_gradient after construction.
    @property
    def stop_gradient(self):
        return self._stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, value):
        self._stop_gradient = bool(value)

    @property
    def trainable(self):
        return not self._stop_gradient

    @trainable.setter
    def trainable(self, value):
        self._stop_gradient = not value

    @property
    def data(self):
        return self

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def dtype(self):
        return dtypes.canonical_name(self._data.dtype)

    @property
    def place(self):
        return device_mod.current_place()

    @property
    def is_leaf(self):
        return self._grad_node is None

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    # -- value access -----------------------------------------------------
    def numpy(self):
        return np.asarray(self._data)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        arr = self.numpy()
        return arr.astype(dtype) if dtype else arr

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        return bool(self.item())

    def __index__(self):
        return int(self.item())

    # -- autograd ---------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False,
                 create_graph=False):
        autograd.backward([self], [grad_tensor], retain_graph=retain_graph,
                          create_graph=create_graph)

    def retain_grads(self):
        self._retain_grad = True

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self):
        self.grad = None

    def detach(self):
        t = Tensor(self._data, stop_gradient=True)
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    # -- mutation facade --------------------------------------------------
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._data
        value = jnp.asarray(value, self._data.dtype)
        if tuple(value.shape) != tuple(self._data.shape):
            raise ValueError(
                "set_value shape mismatch: %s vs %s"
                % (value.shape, self._data.shape))
        self._data = value
        return self

    def copy_(self, other):
        return self.set_value(other)

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        return self

    def fill_(self, v):
        self._data = jnp.full_like(self._data, v)
        return self

    # -- conversions ------------------------------------------------------
    def astype(self, dt):
        from .. import ops
        return ops.cast(self, dt)

    def cast(self, dt):
        return self.astype(dt)

    def clone(self):
        from .. import ops
        return ops.assign(self)

    def cpu(self):
        return self

    def cuda(self, device_id=None, blocking=True):
        # reference VarBase.cuda; placement is XLA's job on this backend
        return self

    def to(self, *a, **k):
        return self

    def pin_memory(self):
        return self

    def value(self):
        # reference VarBase.value() returns the underlying Variable; the
        # Tensor IS the value holder here
        return self

    def gradient(self):
        """reference varbase_patch_methods gradient() — numpy grad or
        None."""
        return None if self.grad is None else self.grad.numpy()

    def contiguous(self):
        return self

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        import jax as _jax
        if isinstance(self._data, _jax.core.Tracer):
            # under jit there is no concrete value to show — raising
            # from repr would turn every print/log of a traced tensor
            # into a TracerArrayConversionError (use @to_static's print
            # conversion to see runtime values)
            return (f"Tensor(shape={self.shape}, dtype={self.dtype}"
                    f"{grad_info}, <traced>)")
        return (f"Tensor(shape={self.shape}, dtype={self.dtype}{grad_info},\n"
                f"       {np.array2string(self.numpy(), threshold=40)})")

    __str__ = __repr__

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # Arithmetic / indexing operators are attached by paddle_tpu.ops at
    # import time (see ops/__init__.py) to avoid an import cycle.


class Parameter(Tensor):
    """Trainable tensor (reference: fluid ParamBase, framework.py:5383)."""

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable,
                         name=name)
        self.persistable = True

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor"""
    if isinstance(data, Tensor):
        if dtype is not None and data.dtype != dtypes.canonical_name(dtype):
            data = data.astype(dtype)
        t = Tensor(data._data, stop_gradient=stop_gradient)
        return t
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
