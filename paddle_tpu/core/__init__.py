from . import dtype, device, flags, rng, autograd, dispatch  # noqa: F401
from .tensor import Tensor, Parameter, to_tensor  # noqa: F401
from .autograd import no_grad, enable_grad, grad_enabled  # noqa: F401
