"""RaggedTensor — true variable-length sequence semantics, TPU-static.

Reference parity: ``paddle/fluid/framework/lod_tensor.h:114`` (LoDTensor:
a flat value tensor + level-0 offsets) and ``operators/sequence_ops/``
computing directly on those offsets.  This closes the representational
gap COVERAGE.md's dense+lengths reduction left open — while keeping
every shape STATIC for XLA:

* ``values`` [capacity, ...]: the flat row-major concatenation of all
  sequences, zero-padded up to a fixed ``capacity`` (pick it from the
  bucketing ladder, exactly like the padded-dense path picks L);
* ``row_splits`` [B+1]: the LoD level-0 offsets;
* positions ≥ ``row_splits[-1]`` belong to a TRASH segment, so every
  segment op runs as one ``jax.ops.segment_*`` with ``num_segments =
  B + 1`` and drops the last row — no data-dependent shapes anywhere,
  one compile per capacity bucket.

Compute on the flat layout does real work proportional to ``capacity``
(total tokens), not ``B × L_max`` — the padded-dense path's cost.  At
the skew measured in BASELINE.md round 3 (median 166 / max 2048) that
is the difference between 17% and 85% waste.

Ops are differentiable (segment_sum/scatter have VJPs); conversion
helpers bridge to the framework's padded+lengths convention.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .tensor import Tensor
from .dispatch import ensure_tensor


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class RaggedTensor:
    """Flat ``values`` + ``row_splits`` (+ static ``capacity``).

    Multi-level (nested) LoD — reference ``lod_tensor.h:114`` where LoD
    is a *vector* of offset levels (paragraphs→sentences→words) — is
    carried as ``outer_lods``: a tuple of offset vectors, outermost
    first, each indexing the rows of the next level; ``row_splits``
    stays the bottom level (the one indexing ``values``), so every
    existing single-level consumer is untouched.  ``lod()`` /
    ``recursive_sequence_lengths()`` match the reference LoDTensor
    accessors."""

    __slots__ = ("values", "row_splits", "capacity", "outer_lods")

    def __init__(self, values, row_splits, outer_lods=()):
        self.values = ensure_tensor(values)
        self.row_splits = ensure_tensor(row_splits)
        self.capacity = int(self.values.shape[0])
        self.outer_lods = tuple(ensure_tensor(s) for s in outer_lods)

    # -- construction -----------------------------------------------------
    @classmethod
    def from_padded(cls, dense, lengths, capacity=None):
        """[B, L, ...] + lengths -> ragged.  ``capacity`` defaults to
        B*L (lossless); pass a bucket size to bound compile variants."""
        dense = ensure_tensor(dense)
        lengths = ensure_tensor(lengths)
        d = dense._data
        lens = lengths._data.astype(jnp.int32)
        B, L = d.shape[0], d.shape[1]
        cap = int(capacity or B * L)
        if not isinstance(lens, jax.core.Tracer):
            total = int(jnp.sum(lens))
            if total > cap:
                raise ValueError(
                    f"RaggedTensor.from_padded: capacity {cap} < total "
                    f"tokens {total} — the scatter would silently drop "
                    "data (pick the bucket like io/bucketing.py does); "
                    "under jit, bounding totals is the CALLER's "
                    "contract")
        splits = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(lens)])
        # scatter each valid (b, t) to its flat slot; padding -> trash
        pos = splits[:-1][:, None] + jnp.arange(L)[None, :]
        valid = jnp.arange(L)[None, :] < lens[:, None]
        slot = jnp.where(valid, pos, cap)            # trash slot = cap
        flat = jnp.zeros((cap + 1,) + d.shape[2:], d.dtype)
        flat = flat.at[slot.reshape(-1)].set(
            d.reshape((B * L,) + d.shape[2:]))
        return cls(Tensor(flat[:cap]), Tensor(splits))

    @staticmethod
    def pack_rows_numpy(rows, capacity=None):
        """Pure-numpy packing -> (flat [cap, ...], row_splits [B+1]).
        DataLoader collate fns use THIS (workers must never touch jax —
        io/worker.py's fork-safety contract)."""
        rows = [np.asarray(r) for r in rows]
        lens = np.array([len(r) for r in rows], np.int32)
        total = int(lens.sum())
        cap = int(capacity or total)
        if cap < total:
            raise ValueError(
                f"RaggedTensor: capacity {cap} < total length {total}")
        tail = rows[0].shape[1:] if rows else ()
        flat = np.zeros((cap,) + tail, rows[0].dtype if rows
                        else np.float32)
        off = 0
        for r in rows:
            flat[off:off + len(r)] = r
            off += len(r)
        splits = np.concatenate([[0], np.cumsum(lens)]).astype(np.int32)
        return flat, splits

    @classmethod
    def from_rows(cls, rows, capacity=None):
        """list of per-row numpy/array values -> ragged (host-side)."""
        flat, splits = cls.pack_rows_numpy(rows, capacity)
        return cls(Tensor(flat), Tensor(splits))

    @classmethod
    def from_nested_rows(cls, nested, capacity=None):
        """Arbitrary-depth nested lists of row arrays -> ragged with
        ``lod_level == depth`` (reference: creating a LoDTensor from
        recursive_sequence_lengths).  Rows must be numpy arrays —
        grouping levels above them are python lists/tuples (a bare
        list-of-scalars row is ambiguous with a grouping level; wrap it
        in np.asarray, or use ``from_rows`` for depth 1)."""
        lods = []
        level = list(nested)
        while level and isinstance(level[0], (list, tuple)):
            counts = np.array([len(g) for g in level], np.int64)
            lods.append(np.concatenate(
                [[0], np.cumsum(counts)]).astype(np.int32))
            level = [item for g in level for item in g]
        flat, splits = cls.pack_rows_numpy(level, capacity)
        return cls(Tensor(flat), Tensor(splits),
                   outer_lods=tuple(Tensor(s) for s in lods))

    # -- views ------------------------------------------------------------
    @property
    def lod_level(self):
        return len(self.outer_lods) + 1

    def lod(self):
        """Offset form, outermost level first — reference
        ``LoDTensor.lod()``."""
        return [list(np.asarray(s.numpy())) for s in self.outer_lods] + \
            [list(np.asarray(self.row_splits.numpy()))]

    def recursive_sequence_lengths(self):
        """Length form per level — reference
        ``LoDTensor.recursive_sequence_lengths()``."""
        out = []
        for off in self.lod():
            a = np.asarray(off)
            out.append(list(a[1:] - a[:-1]))
        return out

    @property
    def nrows(self):
        return int(self.row_splits.shape[0]) - 1

    def lengths(self):
        s = self.row_splits._data
        return Tensor(s[1:] - s[:-1])

    def segment_ids(self):
        """[capacity] int32: row of each flat slot; trash slots get B
        (one past the last row) — THE enabler for segment ops."""
        s = self.row_splits._data
        ids = jnp.searchsorted(s, jnp.arange(self.capacity),
                               side="right") - 1
        total = s[-1]
        return jnp.where(jnp.arange(self.capacity) < total, ids,
                         self.nrows)

    def to_padded(self, max_len, pad_value=0.0):
        """ragged -> ([B, max_len, ...], lengths).  Raises (concrete
        path) when a row exceeds ``max_len`` — silent truncation with
        un-clamped lengths would poison every dense+lengths consumer."""
        v = self.values._data
        s = self.row_splits._data
        B = self.nrows
        lens = s[1:] - s[:-1]
        if not isinstance(lens, jax.core.Tracer) and B:
            longest = int(jnp.max(lens))
            if longest > max_len:
                raise ValueError(
                    f"to_padded: a row has {longest} tokens > max_len "
                    f"{max_len} — raise max_len or slice rows upstream")
        pos = s[:-1][:, None] + jnp.arange(max_len)[None, :]
        valid = jnp.arange(max_len)[None, :] < lens[:, None]
        gathered = v[jnp.clip(pos, 0, self.capacity - 1)]
        dense = jnp.where(
            valid.reshape(valid.shape + (1,) * (v.ndim - 1)), gathered,
            jnp.asarray(pad_value, v.dtype))
        return Tensor(dense), Tensor(lens)

    def to_padded_nested(self, max_rows, max_len, pad_value=0.0):
        """Nested (lod_level >= 2) -> ([G, max_rows, max_len, ...],
        row_lengths [G, max_rows]) using the innermost outer level; for
        deeper nests apply per remaining level.  Reference analogue:
        padding a 2-level LoDTensor batch (sentences per doc, words per
        sentence)."""
        if not self.outer_lods:
            raise ValueError(
                "to_padded_nested: lod_level is 1 — use to_padded")
        dense, lens = self.to_padded(max_len, pad_value)
        d, ln = dense._data, lens._data
        so = self.outer_lods[-1]._data
        B = self.nrows
        G = int(so.shape[0]) - 1
        grp_lens = so[1:] - so[:-1]
        if not isinstance(grp_lens, jax.core.Tracer) and G:
            widest = int(jnp.max(grp_lens))
            if widest > max_rows:
                raise ValueError(
                    f"to_padded_nested: a group has {widest} rows > "
                    f"max_rows {max_rows}")
        pos = so[:-1][:, None] + jnp.arange(max_rows)[None, :]
        valid = jnp.arange(max_rows)[None, :] < grp_lens[:, None]
        g = d[jnp.clip(pos, 0, B - 1)]          # [G, max_rows, L, ...]
        mask = valid.reshape(valid.shape + (1,) * (g.ndim - 2))
        g = jnp.where(mask, g, jnp.asarray(pad_value, g.dtype))
        row_lens = jnp.where(valid, ln[jnp.clip(pos, 0, B - 1)], 0)
        return Tensor(g), Tensor(row_lens)

    def rows(self):
        """Host-side list of per-row numpy arrays (debug/IO)."""
        v = np.asarray(self.values.numpy())
        s = np.asarray(self.row_splits.numpy())
        return [v[s[i]:s[i + 1]] for i in range(len(s) - 1)]

    def nested_rows(self):
        """Host-side nested lists mirroring ``lod_level`` (debug/IO) —
        the inverse of ``from_nested_rows``."""
        out = self.rows()
        for s in reversed(self.outer_lods):
            off = np.asarray(s.numpy())
            out = [out[off[i]:off[i + 1]] for i in range(len(off) - 1)]
        return out


# ---------------------------------------------------------------------------
# segment-compute sequence ops (reference: operators/sequence_ops/*)

def _masked_values(rt):
    """values with trash slots zeroed (so sums ignore them)."""
    v = rt.values._data
    total = rt.row_splits._data[-1]
    live = (jnp.arange(rt.capacity) < total)
    return v * live.reshape((-1,) + (1,) * (v.ndim - 1)).astype(v.dtype)


def sequence_pool(rt: RaggedTensor, pool_type: str, pad_value=0.0):
    """-> [B, ...] (reference: sequence_pool_op.h; SUM/MEAN/SQRT/MAX/
    LAST/FIRST).  Empty rows produce ``pad_value`` like the reference."""
    ids = rt.segment_ids()
    B = rt.nrows
    v = _masked_values(rt)
    lens = rt.lengths()._data.astype(v.dtype)
    ptype = pool_type.lower()
    ptype = {"average": "mean", "avg": "mean"}.get(ptype, ptype)
    if ptype in ("sum", "mean", "sqrt"):
        s = jax.ops.segment_sum(v, ids, num_segments=B + 1)[:B]
        if ptype == "mean":
            s = s / jnp.maximum(lens, 1).reshape(
                (-1,) + (1,) * (v.ndim - 1))
        elif ptype == "sqrt":
            s = s / jnp.sqrt(jnp.maximum(lens, 1)).reshape(
                (-1,) + (1,) * (v.ndim - 1))
        out = s
    elif ptype in ("max", "min"):
        info = jnp.finfo if jnp.issubdtype(v.dtype, jnp.floating) \
            else jnp.iinfo
        fill = info(v.dtype).min if ptype == "max" else \
            info(v.dtype).max
        vm = jnp.where((ids < B).reshape(
            (-1,) + (1,) * (v.ndim - 1)), rt.values._data, fill)
        seg = jax.ops.segment_max if ptype == "max" else \
            jax.ops.segment_min
        out = seg(vm, ids, num_segments=B + 1)[:B]
    elif ptype in ("first", "last"):
        s = rt.row_splits._data
        idx = s[:-1] if ptype == "first" else jnp.maximum(s[1:] - 1, 0)
        out = rt.values._data[jnp.clip(idx, 0, rt.capacity - 1)]
    else:
        raise ValueError(
            f"sequence_pool: unknown pool_type {pool_type!r} "
            "(sum/mean|average/sqrt/max/min/first/last)")
    empty = (rt.lengths()._data == 0).reshape(
        (-1,) + (1,) * (v.ndim - 1))
    out = jnp.where(empty, jnp.asarray(pad_value, out.dtype), out)
    if rt.outer_lods:
        # nested LoD: pooling consumes the bottom level; the result is
        # ragged over the remaining levels (reference: pooling words ->
        # sentence vectors, still LoD-organized by paragraph)
        return RaggedTensor(Tensor(out), rt.outer_lods[-1],
                            outer_lods=rt.outer_lods[:-1])
    return Tensor(out)


def sequence_softmax(rt: RaggedTensor):
    """Row-wise softmax over 1-D-per-step values (reference:
    sequence_softmax_op)."""
    ids = rt.segment_ids()
    B = rt.nrows
    v = rt.values._data
    neg = jnp.finfo(v.dtype).min
    vm = jnp.where(ids < B, v, neg)
    mx = jax.ops.segment_max(vm, ids, num_segments=B + 1)
    live = (ids < B)
    # mask INSIDE exp: exp of the raw (v - finfo.min) would be inf on
    # the untaken branch and the where-VJP's 0*inf turns gradients at
    # trash slots into NaN (the classic jnp.where grad trap)
    ex = live.astype(v.dtype) * jnp.exp(
        jnp.where(live, v - mx[ids], 0.0))
    den = jax.ops.segment_sum(ex, ids, num_segments=B + 1)
    # 1e-38 is denormal — XLA's FTZ would flush it to 0 and
    # make the trash slots 0/0=NaN; stay in normal range
    out = ex / jnp.maximum(den[ids], 1e-30)
    return RaggedTensor(Tensor(out), rt.row_splits,
                        outer_lods=rt.outer_lods)


def sequence_reverse(rt: RaggedTensor):
    """Reverse each row in place (reference: sequence_reverse_op)."""
    ids = rt.segment_ids()
    B = rt.nrows
    s = rt.row_splits._data
    pos = jnp.arange(rt.capacity)
    ids_c = jnp.clip(ids, 0, B - 1)
    # mirror within the row: start + end-1 - pos
    src = s[ids_c] + (s[ids_c + 1] - 1) - pos
    src = jnp.where(ids < B, src, pos)
    out = rt.values._data[jnp.clip(src, 0, rt.capacity - 1)]
    return RaggedTensor(Tensor(out), rt.row_splits,
                        outer_lods=rt.outer_lods)


def _level_splits(rt: RaggedTensor, level):
    """Offset vector of a LoD level (0 = outermost, -1 = bottom)."""
    all_lods = rt.outer_lods + (rt.row_splits,)
    return all_lods[level]._data


def sequence_expand(rt: RaggedTensor, ref: RaggedTensor, ref_level=-1,
                    capacity=None, max_out_rows=None, one_step=None):
    """Reference ``sequence_expand_op.cc``: repeat x's row i
    ``ref_len[i]`` times, where ``ref_len`` are the lengths of ref's
    LoD level ``ref_level``.

    Two regimes, matching the reference's two uses:

    * all x rows are single-step and ``ref_level`` is the bottom level
      — the broadcast/expand_as pattern (CTR models): x's step i is
      broadcast across ref's row i; output has ref's LoD.
    * general whole-row repeat (nested beam-search/NMT pattern): each
      x ROW is copied ``ref_len[i]`` times; the output gains an outer
      LoD level grouping the copies (lod_level 2, mirroring the
      reference where out LoD = ref-level offsets over x's LoD).
      Shapes stay static: pass ``capacity`` (total out steps bound) and
      ``max_out_rows`` under jit; both default to the exact concrete
      totals outside jit.

    Under jit the x row lengths are traced, so the two regimes cannot
    be told apart: pass ``one_step=True`` to assert the broadcast
    pattern, or ``capacity``/``max_out_rows`` for the whole-row repeat.
    Neither raises — a silent one-step fallback on multi-step rows
    would return only each row's first step.
    """
    rl_splits = _level_splits(ref, ref_level)
    rl = (rl_splits[1:] - rl_splits[:-1]).astype(jnp.int32)
    N = int(rl.shape[0])
    if rt.nrows != N:
        raise ValueError(
            f"sequence_expand: x has {rt.nrows} rows but ref level "
            f"{ref_level} has {N} entries")
    x_lens = rt.lengths()._data
    lens_traced = isinstance(x_lens, jax.core.Tracer)
    if not lens_traced:
        concrete_one = bool(jnp.all(x_lens == 1))
        if one_step and not concrete_one:
            raise ValueError(
                "sequence_expand: one_step=True but x has multi-step "
                "rows")
        one_step = concrete_one
    elif one_step is None:
        if capacity is None and max_out_rows is None:
            raise ValueError(
                "sequence_expand: x row lengths are traced (jit) and no "
                "bounds were given — pass one_step=True for the "
                "broadcast/expand_as pattern, or capacity/max_out_rows "
                "for the whole-row repeat (a silent one-step fallback "
                "would return only each row's first step)")
        one_step = False
    if one_step and ref_level in (-1, ref.lod_level - 1):
        # broadcast fast path: one gather, output keeps ref's LoD
        ids = ref.segment_ids()
        B = ref.nrows
        x_first = rt.values._data[
            jnp.clip(rt.row_splits._data[:-1], 0, rt.capacity - 1)]
        out = x_first[jnp.clip(ids, 0, B - 1)]
        live = (ids < B).reshape((-1,) + (1,) * (out.ndim - 1))
        out = out * live.astype(out.dtype)
        return RaggedTensor(Tensor(out), ref.row_splits,
                            outer_lods=ref.outer_lods)

    # general whole-row repeat, static-shaped
    r_cum = jnp.cumsum(rl)
    r_total = r_cum[-1]
    if max_out_rows is None:
        if isinstance(r_total, jax.core.Tracer):
            raise ValueError(
                "sequence_expand: pass max_out_rows under jit — the "
                "repeated row count is data-dependent")
        max_out_rows = int(r_total)
    elif not isinstance(r_total, jax.core.Tracer) and \
            int(r_total) > max_out_rows:
        raise ValueError(
            f"sequence_expand: max_out_rows {max_out_rows} < actual "
            f"repeated row count {int(r_total)} — the result would "
            "silently drop rows")
    r = jnp.arange(max_out_rows)
    grp = jnp.searchsorted(r_cum, r, side="right")     # x row per out row
    grp_c = jnp.clip(grp, 0, N - 1)
    live_row = r < r_total
    sx = rt.row_splits._data
    out_len = jnp.where(live_row, sx[grp_c + 1] - sx[grp_c], 0)
    out_splits = jnp.concatenate(
        [jnp.zeros(1, jnp.int32),
         jnp.cumsum(out_len)]).astype(jnp.int32)
    total_steps = out_splits[-1]
    if capacity is None:
        if isinstance(total_steps, jax.core.Tracer):
            raise ValueError(
                "sequence_expand: pass capacity under jit — the total "
                "output step count is data-dependent")
        capacity = int(total_steps)
    elif not isinstance(total_steps, jax.core.Tracer) and \
            int(total_steps) > capacity:
        raise ValueError(
            f"sequence_expand: capacity {capacity} < actual output "
            f"step count {int(total_steps)} — the scatter would "
            "silently drop data (pick the bucket like io/bucketing.py)")
    p = jnp.arange(capacity)
    row_of_p = jnp.searchsorted(out_splits, p, side="right") - 1
    row_c = jnp.clip(row_of_p, 0, max_out_rows - 1)
    local = p - out_splits[row_c]
    src = sx[jnp.clip(grp[row_c], 0, N - 1)] + local
    vals = rt.values._data[jnp.clip(src, 0, rt.capacity - 1)]
    live = (p < total_steps).reshape((-1,) + (1,) * (vals.ndim - 1))
    vals = vals * live.astype(vals.dtype)
    outer = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), r_cum]).astype(jnp.int32)
    return RaggedTensor(Tensor(vals), Tensor(out_splits),
                        outer_lods=(Tensor(outer),))


def sequence_concat(a: RaggedTensor, b: RaggedTensor):
    """Row-wise concat: out row i = a row i ++ b row i (reference:
    sequence_concat_op).  Nested inputs must agree on their outer
    levels; the output carries them unchanged (bottom-level concat
    leaves the grouping structure intact)."""
    if a.nrows != b.nrows:
        raise ValueError("sequence_concat: row counts differ")
    if len(a.outer_lods) != len(b.outer_lods):
        raise ValueError("sequence_concat: lod_level mismatch")
    for sa_, sb_ in zip(a.outer_lods, b.outer_lods):
        da, db = sa_._data, sb_._data
        if not (isinstance(da, jax.core.Tracer)
                or isinstance(db, jax.core.Tracer)):
            if da.shape != db.shape or not bool(jnp.all(da == db)):
                raise ValueError(
                    "sequence_concat: outer LoD levels differ between "
                    "inputs")
    sa, sb = a.row_splits._data, b.row_splits._data
    la, lb = sa[1:] - sa[:-1], sb[1:] - sb[:-1]
    splits = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(la + lb)]).astype(jnp.int32)
    cap = a.capacity + b.capacity
    B = a.nrows

    def scatter(src_vals, src_splits, dst, base_off):
        ids = jnp.searchsorted(
            src_splits, jnp.arange(src_vals.shape[0]),
            side="right") - 1
        total = src_splits[-1]
        live = jnp.arange(src_vals.shape[0]) < total
        ids_c = jnp.clip(ids, 0, B - 1)
        local = jnp.arange(src_vals.shape[0]) - src_splits[ids_c]
        slot = splits[ids_c] + base_off[ids_c] + local
        slot = jnp.where(live, slot, cap)
        return dst.at[slot].set(src_vals)

    tail = a.values._data.shape[1:]
    dst = jnp.zeros((cap + 1,) + tail, a.values._data.dtype)
    dst = scatter(a.values._data, sa, dst, jnp.zeros(B, jnp.int32))
    dst = scatter(b.values._data, sb, dst, la.astype(jnp.int32))
    return RaggedTensor(Tensor(dst[:cap]), Tensor(splits),
                        outer_lods=a.outer_lods)
