"""Device management.

Reference parity: ``paddle/fluid/platform/place.h`` (CPUPlace/CUDAPlace/...)
and ``python/paddle/device.py`` (set_device/get_device).  On TPU there is a
single logical device kind per process; `set_device("tpu")`/"cpu" selects the
jax backend used for new tensors.  Multi-chip execution is expressed through
``paddle_tpu.distributed`` meshes, not through per-op device placement.
"""
from __future__ import annotations

import jax

_current_device = None  # lazily resolved


class Place:
    """Device identity (reference: platform/place.h:26-103)."""

    def __init__(self, kind: str, index: int = 0):
        self.kind = kind
        self.index = index

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"

    def __eq__(self, other):
        return (isinstance(other, Place) and self.kind == other.kind
                and self.index == other.index)

    def is_tpu_place(self):
        return self.kind == "tpu"

    def is_cpu_place(self):
        return self.kind == "cpu"


def CPUPlace():
    return Place("cpu", 0)


def TPUPlace(idx: int = 0):
    return Place("tpu", idx)


def _default_kind() -> str:
    try:
        backend = jax.default_backend()
    except Exception:
        return "cpu"
    if backend in ("cpu",):
        return "cpu"
    return "tpu"  # tpu / axon / any accelerator


def set_device(device: str):
    """paddle.set_device — 'tpu', 'tpu:0', 'cpu'."""
    global _current_device
    kind, _, idx = device.partition(":")
    kind = {"gpu": "tpu", "xpu": "tpu", "tpu": "tpu", "cpu": "cpu"}.get(kind)
    if kind is None:
        raise ValueError("unknown device %r (use 'tpu' or 'cpu')" % device)
    _current_device = Place(kind, int(idx) if idx else 0)
    return _current_device


def get_device() -> str:
    p = current_place()
    return f"{p.kind}:{p.index}"


def current_place() -> Place:
    global _current_device
    if _current_device is None:
        _current_device = Place(_default_kind(), 0)
    return _current_device


def jax_device(place: Place | None = None):
    """Resolve a Place to a concrete jax device object."""
    place = place or current_place()
    if place.kind == "cpu":
        devs = jax.devices("cpu")
    else:
        devs = jax.devices()
    return devs[min(place.index, len(devs) - 1)]


def device_count() -> int:
    return len(jax.devices())


def is_compiled_with_cuda() -> bool:  # API-compat shim
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True
