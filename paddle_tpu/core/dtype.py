"""Dtype system.

Reference parity: paddle's VarType dtypes (reference
``paddle/fluid/framework/framework.proto`` VarType.Type) exposed as string
dtypes mapped onto jax/numpy dtypes.  Default dtype is float32, switchable
via ``set_default_dtype`` (reference ``python/paddle/framework/dtype.py``).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

# canonical name -> jnp dtype
_DTYPE_MAP = {
    "bool": jnp.bool_,
    "uint8": jnp.uint8,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
}

_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "int": "int32",
    "long": "int64",
    "bfloat": "bfloat16",
}

bool_ = "bool"
uint8 = "uint8"
int8 = "int8"
int16 = "int16"
int32 = "int32"
int64 = "int64"
float16 = "float16"
bfloat16 = "bfloat16"
float32 = "float32"
float64 = "float64"
complex64 = "complex64"
complex128 = "complex128"

_default_dtype = "float32"


def set_default_dtype(d):
    """Set default floating dtype (paddle.set_default_dtype)."""
    global _default_dtype
    name = canonical_name(d)
    if name not in ("float16", "bfloat16", "float32", "float64"):
        raise TypeError(
            "set_default_dtype only supports floating dtypes, got %s" % d)
    _default_dtype = name


def get_default_dtype():
    return _default_dtype


def canonical_name(d) -> str:
    """Normalize any dtype spec (str, np.dtype, jnp dtype) to canonical str."""
    if d is None:
        return _default_dtype
    if isinstance(d, str):
        name = _ALIASES.get(d, d)
        if name in _DTYPE_MAP:
            return name
        raise TypeError("unsupported dtype: %r" % (d,))
    # jnp scalar types / np.dtype
    try:
        name = np.dtype(d).name
    except TypeError:
        name = getattr(d, "__name__", None) or str(d)
    if name == "bfloat16" or "bfloat16" in str(d):
        return "bfloat16"
    name = _ALIASES.get(name, name)
    if name in _DTYPE_MAP:
        return name
    raise TypeError("unsupported dtype: %r" % (d,))


def to_jax(d):
    """Any dtype spec -> jnp dtype class."""
    return _DTYPE_MAP[canonical_name(d)]


def is_floating(d) -> bool:
    return canonical_name(d) in ("float16", "bfloat16", "float32", "float64")


def is_integer(d) -> bool:
    return canonical_name(d) in ("uint8", "int8", "int16", "int32", "int64")


def is_complex(d) -> bool:
    return canonical_name(d) in ("complex64", "complex128")
