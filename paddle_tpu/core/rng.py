"""Random state.

Reference parity: ``paddle/fluid/framework/generator.cc`` (global & per-device
generators, seed control via ``paddle.seed``).  TPU-native design: a single
process-level counter-based PRNG built on jax's threefry keys; every consumer
draws a fresh split so eager calls are reproducible under a fixed seed.
Inside jit'd training steps, keys are threaded functionally.
"""
from __future__ import annotations

import os
import threading

import jax

# TPU-native default: the rbg PRNG implementation maps directly onto the
# TPU's hardware RNG instruction, where threefry burns vector cycles
# generating counter bits (measured +4.4% GPT-2 345M train throughput on
# v5e with per-layer dropout).  The reference has per-backend RNG anyway
# (curand on GPU), so cross-impl bit-exactness was never the contract.
# Opt out with PADDLE_TPU_PRNG=threefry.
_prng_impl = os.environ.get("PADDLE_TPU_PRNG", "rbg")
if _prng_impl != "threefry":
    try:
        # an import side effect that changes random streams process-wide
        # deserves a trace: WARNING (visible under default logging) when
        # it clobbers a value someone else configured, INFO otherwise —
        # a stderr line on every ordinary import would be noise
        import logging
        _prev = getattr(jax.config, "jax_default_prng_impl",
                        "threefry2x32")
        jax.config.update("jax_default_prng_impl", _prng_impl)
        _log = logging.getLogger(__name__)
        _msg = ("paddle_tpu set jax_default_prng_impl=%s (TPU hardware "
                "RNG; random streams differ from threefry-based runs — "
                "opt out with PADDLE_TPU_PRNG=threefry)")
        if _prev not in ("threefry2x32", _prng_impl):
            _log.warning(_msg + " [overrode existing setting %r]",
                         _prng_impl, _prev)
        else:
            _log.info(_msg, _prng_impl)
    except AttributeError:
        # only "this jax has no such config knob" is ignorable; anything
        # else (e.g. an invalid PADDLE_TPU_PRNG value) must surface
        pass

_lock = threading.Lock()
_seed = 0
# created lazily: building a key runs a jit computation, and importing the
# package must not initialize the jax backend (embedded/C-API callers select
# the platform after import)
_key = None


def seed(s: int):
    """paddle.seed — reset the global generator."""
    global _key, _seed
    with _lock:
        _seed = int(s)
        _key = jax.random.key(_seed)
    # a fresh seed promises fresh initialization: drop memoized named
    # parameters (incubate.LayerHelper) so rebuilt models don't silently
    # reuse trained weights from a previous model's life
    try:
        from ..incubate import LayerHelper
        LayerHelper.clear_registry()
    except ImportError:
        pass
    return _seed


def get_seed() -> int:
    return _seed


# When tracing a jit'd step, a traced key is pushed here so that stochastic
# ops (dropout etc.) fold into it instead of baking in a host-side constant.
_trace_stack: list = []


def push_trace_key(key):
    _trace_stack.append([key, 0])


def pop_trace_key():
    _trace_stack.pop()


def in_traced_region() -> bool:
    return bool(_trace_stack)


def next_key():
    """Draw a fresh subkey: from the traced key inside a traced training
    step (deterministic per-call fold_in), else from the global generator."""
    if _trace_stack:
        entry = _trace_stack[-1]
        entry[1] += 1
        return jax.random.fold_in(entry[0], entry[1])
    global _key
    with _lock:
        if _key is None:
            _key = jax.random.key(_seed)
        _key, sub = jax.random.split(_key)
    return sub


def op_key(*inputs):
    """Key for a stochastic op over the given Tensor inputs.

    If any input is a static-graph Variable, returns a symbolic key that
    the Executor replaces with a fresh key each run (so e.g. dropout masks
    differ per iteration — reference dropout_op.cc per-execution seeds);
    otherwise draws from the global/traced stream."""
    try:
        from ..static import program as sprog
        if sprog.in_static_mode() and any(
                isinstance(a, sprog.Variable) for a in inputs):
            return sprog.default_main_program().rng_key_var()
    except ImportError:
        pass
    return next_key()


def key_for(seed_val: int | None):
    """Key from an explicit seed, or the global stream if None/0."""
    if seed_val:
        return jax.random.key(int(seed_val))
    return next_key()


def request_key(seed_lo, seed_hi):
    """Key for a serving request's sampling stream, built from the
    seed's two 32-bit words (``Request.seed_words()``): jax without
    x64 demotes int64 inputs to int32, so a 63-bit request seed must
    travel as two uint32 lanes and fold back together here.  Works
    with concrete ints AND traced uint32 values — the serving engine's
    fused on-device sampling vmaps this over the slot pool, and the
    eager first-token pick calls it with the same words, so the two
    paths draw from one stream (key = fold(request_key, token_index))."""
    return jax.random.fold_in(jax.random.key(seed_lo), seed_hi)
