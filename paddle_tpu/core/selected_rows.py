"""Sparse row-wise gradients — the SelectedRows analogue.

Reference parity: ``paddle/fluid/framework/selected_rows.h`` (rows + value
tensor over a dense height) and ``imperative/gradient_accumulator.cc``
(SelectedRows-aware grad summing).  In the reference, ``nn.Embedding(...,
sparse=True)`` makes the lookup_table backward emit SelectedRows so a
vocab-sized dense cotangent never materializes; optimizer sparse kernels
(adam/sgd with SelectedRows input) then update only the touched rows.

TPU-native design: a ``SelectedRows`` IS a Tensor whose dense form is
computed lazily.  Sparse-aware consumers (the eager tape's leaf
accumulator, ``Optimizer.step``, ``ClipGradByGlobalNorm``) read
``.rows()`` / ``.merged()`` and never densify; any unaware consumer that
touches ``._data`` (user ``.numpy()``, an optimizer without a sparse rule)
transparently gets the scatter-added dense array — correctness everywhere,
sparsity where it matters.  Under jit/static the tape is off and XLA's
fused scatter-add on the gather VJP plays this role instead (one kernel,
no intermediate), so this class is an eager-path construct by design.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .tensor import Tensor

# the base class's slot descriptor for ``_data`` — the subclass property
# shadows it, so dense storage goes through the descriptor explicitly
_DENSE = Tensor.__dict__["_data"]


class SelectedRows(Tensor):
    """{rows, values} over a dense ``[height, *dim]`` gradient.

    ``rows`` may contain duplicates (one entry per lookup); ``merged()``
    returns the deduplicated, segment-summed form that sparse optimizer
    rules consume (reference: operators/math/selected_rows_functor.cc
    MergeAdd).
    """

    def __init__(self, rows, values, height, name=None):
        self._rows = jnp.asarray(rows).reshape(-1)
        self._values = jnp.asarray(values)
        if self._values.shape[0] != self._rows.shape[0]:
            raise ValueError(
                "SelectedRows: values.shape[0] (%d) != len(rows) (%d)"
                % (self._values.shape[0], self._rows.shape[0]))
        self._height = int(height)
        self._merged_cache = None
        _DENSE.__set__(self, None)
        self._stop_gradient = True
        self.grad = None
        self._grad_node = None
        self._retain_grad = False
        Tensor._next_id[0] += 1
        self.name = name or f"selected_rows_{Tensor._next_id[0]}"
        self.persistable = False

    # -- sparse surface ---------------------------------------------------
    @property
    def rows(self):
        return self._rows

    @property
    def values(self):
        return self._values

    @property
    def height(self):
        return self._height

    def merged(self):
        """(unique_rows, segment-summed values); cached."""
        if self._merged_cache is None:
            uniq, inv = jnp.unique(self._rows, return_inverse=True)
            vals = jax.ops.segment_sum(
                self._values, inv.reshape(-1),
                num_segments=int(uniq.shape[0]))
            self._merged_cache = (uniq, vals)
        return self._merged_cache

    def append(self, other: "SelectedRows") -> "SelectedRows":
        """Sparse + sparse accumulation: concatenate (reference:
        gradient_accumulator.cc keeps a row list and merges lazily)."""
        if other._height != self._height or \
                other._values.shape[1:] != self._values.shape[1:]:
            raise ValueError("SelectedRows shape mismatch in accumulation")
        return SelectedRows(
            jnp.concatenate([self._rows, other._rows]),
            jnp.concatenate([self._values,
                             other._values.astype(self._values.dtype)]),
            self._height)

    def is_densified(self):
        return _DENSE.__get__(self) is not None

    @classmethod
    def from_merged(cls, rows, values, height):
        """Construct from rows already known unique — primes the merged
        cache so consumers skip the unique+segment_sum pass."""
        out = cls(rows, values, height)
        out._merged_cache = (out._rows, out._values)
        return out

    # -- Tensor compatibility --------------------------------------------
    @property
    def _data(self):
        d = _DENSE.__get__(self)
        if d is None:
            d = jnp.zeros((self._height,) + tuple(self._values.shape[1:]),
                          self._values.dtype)
            d = d.at[self._rows].add(self._values)
            _DENSE.__set__(self, d)
        return d

    @_data.setter
    def _data(self, v):
        # In-place grad mutators (amp.GradScaler.unscale_, clip_grad_norm_)
        # assign the dense array directly.  The sparse view must follow or
        # sparse-aware consumers (Optimizer.step via merged()) would keep
        # applying the STALE pre-mutation values — so densification is the
        # representation from here on: rows become [0..height), values the
        # dense array, and merged() is free (already unique).
        v = jnp.asarray(v)
        _DENSE.__set__(self, v)
        self._rows = jnp.arange(self._height, dtype=jnp.int32)
        self._values = v
        self._merged_cache = (self._rows, self._values)

    @property
    def shape(self):
        # metadata must not force densification
        return [self._height] + list(self._values.shape[1:])

    @property
    def ndim(self):
        return self._values.ndim

    @property
    def size(self):
        import numpy as np
        return int(np.prod(self.shape))

    @property
    def dtype(self):
        from . import dtype as dtypes
        return dtypes.canonical_name(self._values.dtype)

    def __len__(self):
        return self._height

    def __repr__(self):
        return (f"SelectedRows(height={self._height}, "
                f"nnz_rows={int(self._rows.shape[0])}, "
                f"dim={list(self._values.shape[1:])}, "
                f"dtype={self._values.dtype})")
