"""Eager reverse-mode autodiff engine.

Reference parity: the dygraph tape + BasicEngine
(``paddle/fluid/imperative/tracer.cc:132`` records grad nodes;
``basic_engine.cc:39,221,265`` executes them;
``gradient_accumulator.cc`` sums incoming grads).

TPU-native design: instead of per-op registered grad kernels, every traced op
captures a ``jax.vjp`` closure at forward time.  ``backward()`` walks nodes in
reverse creation order (a valid topological order for an eagerly-built tape)
and accumulates cotangents.  The jit/static path does NOT use this tape — it
uses ``jax.grad`` over a functional step (see paddle_tpu.jit / hapi), which is
where performance comes from; this engine exists for eager ergonomics parity.
"""
from __future__ import annotations

import contextlib
import threading
import weakref

import jax
import jax.numpy as jnp

_state = threading.local()


def _tls():
    if not hasattr(_state, "enabled"):
        _state.enabled = True
    return _state


def grad_enabled() -> bool:
    return _tls().enabled


@contextlib.contextmanager
def no_grad():
    """paddle.no_grad — disable tape recording."""
    tls = _tls()
    prev = tls.enabled
    tls.enabled = False
    try:
        yield
    finally:
        tls.enabled = prev


@contextlib.contextmanager
def enable_grad():
    tls = _tls()
    prev = tls.enabled
    tls.enabled = True
    try:
        yield
    finally:
        tls.enabled = prev


_node_counter = [0]


class GradNode:
    """One recorded op: inputs that require grad + the vjp closure.

    Holds STRONG refs to differentiable input tensors (keeps the upstream
    graph alive) and WEAK refs to outputs (so dead branches are collectable).
    """

    __slots__ = ("id", "inputs", "out_refs", "out_meta", "vjp_fn", "name",
                 "primal_fn", "primal_in", "out_container",
                 "primal_has_aux", "__weakref__")

    def __init__(self, inputs, outputs, vjp_fn, name=""):
        _node_counter[0] += 1
        self.id = _node_counter[0]
        self.inputs = inputs                      # list[Tensor]
        self.out_refs = [weakref.ref(o) for o in outputs]
        self.out_meta = [(o.shape, o._data.dtype) for o in outputs]
        self.vjp_fn = vjp_fn                      # cotangents tuple -> input grads
        self.name = name
        # double-grad support (reference: imperative/partial_grad_engine.cc):
        # the dispatcher stashes the op's pure forward + primal arrays so
        # create_graph=True can re-derive d(vjp)/d(primal) — the term a
        # closure-only vjp application would silently drop.
        self.primal_fn = None     # pure fn(*primal_in) -> out structure
        self.primal_in = None     # tuple of arrays at record time
        self.out_container = None  # tuple/list type of fn output, or None
        self.primal_has_aux = False

    def outputs_alive(self):
        return [r() for r in self.out_refs]


def snapshot_for_inplace(t):
    """Freeze `t`'s current graph identity into a fresh Tensor so an
    in-place op can rebuild `t` on top of it.  The producing node's weak
    output ref is re-pointed at the snapshot, keeping the upstream chain
    intact after `t` is mutated."""
    from .tensor import Tensor
    old = Tensor(t._data, stop_gradient=t.stop_gradient)
    node = t._grad_node
    old._grad_node = node
    old._retain_grad = t._retain_grad
    if node is not None:
        for i, ref in enumerate(node.out_refs):
            if ref() is t:
                node.out_refs[i] = weakref.ref(old)
    return old


def adopt_result(target, out):
    """Make `target` take over `out`'s value AND its place in the graph
    (used by in-place ops: reshape_, __setitem__).  Rebinds the producing
    node's weak output ref so backward seeds reach it.  The op producing
    `out` must have consumed ``snapshot_for_inplace(target)``, NOT target
    itself, or the upstream chain is lost."""
    node = out._grad_node
    target._data = out._data
    target._grad_node = node
    target.stop_gradient = out.stop_gradient
    if node is not None:
        for i, ref in enumerate(node.out_refs):
            if ref() is out:
                node.out_refs[i] = weakref.ref(target)
    return target


def run_inplace(target, op, *args, **kwargs):
    """Execute ``op`` as the in-place realization of ``target``."""
    old = snapshot_for_inplace(target)
    out = op(old, *args, **kwargs)
    return adopt_result(target, out)


def record(inputs, outputs, vjp_fn, name=""):
    """Attach a GradNode to output tensors (called by the op dispatcher)."""
    node = GradNode(inputs, outputs, vjp_fn, name)
    for o in outputs:
        o._grad_node = node
        o.stop_gradient = False
    return node


def _collect_nodes(root_nodes):
    """All nodes reachable from the roots, sorted by creation id descending."""
    seen = {}
    stack = list(root_nodes)
    while stack:
        node = stack.pop()
        if node is None or node.id in seen:
            continue
        seen[node.id] = node
        for t in node.inputs:
            if t._grad_node is not None:
                stack.append(t._grad_node)
    return sorted(seen.values(), key=lambda n: -n.id)


def backward(tensors, grad_tensors=None, retain_graph=False,
             create_graph=False, _leaf_targets=None):
    """Run reverse mode from `tensors` (reference: basic_engine.cc:265).

    Leaf tensors (stop_gradient=False, no grad node) receive ``.grad``.
    Non-leaf tensors receive ``.grad`` only if ``retain_grads()`` was called.
    With ``create_graph=True`` the backward computation itself is recorded
    on the tape (reference: imperative/partial_grad_engine.cc — double
    grad), so the produced ``.grad`` tensors are differentiable.
    ``_leaf_targets`` (set of tensor ids) restricts which tensors receive
    ``.grad`` — ``paddle.grad`` uses it so leaves outside ``inputs`` are
    not polluted (PartialGradEngine semantics).
    """
    from .tensor import Tensor

    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    if create_graph:
        return _backward_create_graph(tensors, grad_tensors, retain_graph,
                                      _leaf_targets)

    # cotangent store keyed by id(tensor); tensors kept alive by node refs
    grads: dict[int, jax.Array] = {}
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "backward() on a non-scalar tensor requires explicit "
                    "grad_tensors (got shape %s)" % (t.shape,))
            g_arr = jnp.ones(t.shape, t._data.dtype)
        else:
            g_arr = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        grads[id(t)] = grads.get(id(t), 0) + g_arr

    def _want(t):
        return _leaf_targets is None or id(t) in _leaf_targets

    roots = [t._grad_node for t in tensors if t._grad_node is not None]
    # seed leaves passed directly
    for t in tensors:
        if t._grad_node is None and not t.stop_gradient and _want(t):
            _accumulate_leaf(t, grads[id(t)])

    for node in _collect_nodes(roots):
        outs = node.outputs_alive()
        cotangents = []
        any_seed = False
        for ref, (shape, dtype) in zip(outs, node.out_meta):
            g = grads.pop(id(ref), None) if ref is not None else None
            if g is None:
                cotangents.append(jnp.zeros(shape, dtype))
            else:
                any_seed = True
                if _is_selected_rows(g):
                    # sparse cotangent flowing INTO an op (the consumed
                    # tensor was itself produced by an op): densify — only
                    # leaf accumulation stays sparse end-to-end
                    g = g._data
                cotangents.append(jnp.asarray(g, dtype))
        if not any_seed:
            continue
        ct = tuple(cotangents) if len(cotangents) > 1 else cotangents[0]
        in_grads = node.vjp_fn(ct)
        if not isinstance(in_grads, tuple):
            in_grads = (in_grads,)
        for t, g in zip(node.inputs, in_grads):
            if g is None:
                continue
            if t._grad_node is None:
                if _want(t):
                    _accumulate_leaf(t, g)
            else:
                grads[id(t)] = _sum(grads.get(id(t)), g)
                if t._retain_grad and _want(t):
                    _accumulate_leaf(t, g)
        if not retain_graph:
            # keep the node (so a second backward raises via _freed_vjp)
            # but drop the closures and their forward residuals
            node.vjp_fn = _freed_vjp
            node.primal_fn = None
            node.primal_in = None


def _freed_vjp(*_):
    raise RuntimeError(
        "Trying to backward through the graph a second time; "
        "pass retain_graph=True to backward() if needed.")


# ---------------------------------------------------------------------------
# double grad (create_graph=True)
#
# Reference: imperative/partial_grad_engine.cc — PartialGradEngine builds
# grad-of-grad nodes.  Here each node's vjp application is re-dispatched as
# a RECORDED op over (cotangents, original primal inputs): jax re-derives
# the vjp from the stashed pure forward, so the produced gradients depend
# differentiably on BOTH the cotangents and the primals (the x-dependence a
# closure-only vjp application would treat as constant).

def _apply_grad_op(node, ct_tensors):
    from .tensor import Tensor
    container = node.out_container
    n_ct = len(ct_tensors)

    def gop(*flat):
        cts, prim = flat[:n_ct], flat[n_ct:]
        if node.primal_has_aux:
            _, vjp2, _ = jax.vjp(node.primal_fn, *prim, has_aux=True)
        else:
            _, vjp2 = jax.vjp(node.primal_fn, *prim)
        ct_struct = container(cts) if container is not None else cts[0]
        return tuple(vjp2(ct_struct))

    inputs_all = list(ct_tensors) + list(node.inputs)
    arrays_all = [t._data for t in ct_tensors] + list(node.primal_in)
    diff_idx = [i for i, t in enumerate(inputs_all)
                if not t.stop_gradient and
                jnp.issubdtype(t._data.dtype, jnp.floating)]
    if not (diff_idx and grad_enabled()):
        return [Tensor(o, stop_gradient=True) for o in gop(*arrays_all)]

    def closed(*diff_arrays):
        full = list(arrays_all)
        for i, d in zip(diff_idx, diff_arrays):
            full[i] = d
        return gop(*full)

    primal_in = tuple(arrays_all[i] for i in diff_idx)
    out, vjp_fn = jax.vjp(closed, *primal_in)
    out_t = [Tensor(o, stop_gradient=False) for o in out]
    node2 = record([inputs_all[i] for i in diff_idx], out_t,
                   lambda ct: vjp_fn(ct if isinstance(ct, tuple)
                                     else (ct,)),
                   (node.name or "op") + "_grad")
    node2.primal_fn = closed
    node2.primal_in = primal_in
    node2.out_container = tuple
    return out_t


def _backward_create_graph(tensors, grad_tensors, retain_graph,
                           _leaf_targets=None):
    from .tensor import Tensor

    grads: dict[int, "Tensor"] = {}

    def _want(t):
        return _leaf_targets is None or id(t) in _leaf_targets

    def _tadd(a, b):
        return b if a is None else a + b  # Tensor add: recorded

    for t, g in zip(tensors, grad_tensors):
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "backward() on a non-scalar tensor requires explicit "
                    "grad_tensors (got shape %s)" % (t.shape,))
            g_t = Tensor(jnp.ones(t.shape, t._data.dtype),
                         stop_gradient=True)
        else:
            g_t = g if isinstance(g, Tensor) else Tensor(jnp.asarray(g),
                                                         stop_gradient=True)
        grads[id(t)] = _tadd(grads.get(id(t)), g_t)

    roots = [t._grad_node for t in tensors if t._grad_node is not None]
    for t in tensors:
        if t._grad_node is None and not t.stop_gradient and _want(t):
            _accumulate_leaf_tensor(t, grads[id(t)])

    for node in _collect_nodes(roots):
        if node.vjp_fn is _freed_vjp:
            _freed_vjp()
        outs = node.outputs_alive()
        cotangents = []
        any_seed = False
        for ref, (shape, dtype) in zip(outs, node.out_meta):
            g = grads.pop(id(ref), None) if ref is not None else None
            if g is None:
                cotangents.append(Tensor(jnp.zeros(shape, dtype),
                                         stop_gradient=True))
            else:
                any_seed = True
                if g._data.dtype != dtype:
                    g = _recorded_cast(g, dtype)
                cotangents.append(g)
        if not any_seed:
            continue
        if node.primal_fn is None:
            raise RuntimeError(
                f"double grad through op '{node.name}': no primal record "
                "(create_graph=True requires dispatcher-recorded ops)")
        in_grads = _apply_grad_op(node, cotangents)
        for t, g in zip(node.inputs, in_grads):
            if g is None:
                continue
            if t._grad_node is None:
                if _want(t):
                    _accumulate_leaf_tensor(t, g)
            else:
                grads[id(t)] = _tadd(grads.get(id(t)), g)
                if t._retain_grad and _want(t):
                    _accumulate_leaf_tensor(t, g)
        # nodes are never freed under create_graph: the produced grad
        # graph references them for the next-order backward


def _recorded_cast(g, dtype):
    """Cast through the dispatched op so a graph-carrying gradient keeps
    its differentiable history (a bare Tensor(asarray(...)) would drop the
    grad node and silently zero higher-order terms)."""
    from .tensor import Tensor
    if g.stop_gradient and g._grad_node is None:
        return Tensor(jnp.asarray(g._data, dtype), stop_gradient=True)
    from ..ops import cast as ops_cast
    return ops_cast(g, jnp.dtype(dtype).name)


def _accumulate_leaf_tensor(t, g):
    """Accumulate a (possibly graph-carrying) Tensor gradient."""
    if g._data.dtype != t._data.dtype:
        g = _recorded_cast(g, t._data.dtype)
    if t.grad is None:
        t.grad = g
    else:
        t.grad = t.grad + g


def _is_selected_rows(g):
    from .selected_rows import SelectedRows
    return isinstance(g, SelectedRows)


def _sum(a, b):
    if a is None:
        return b
    # sparse/sparse accumulation stays sparse (reference:
    # gradient_accumulator.cc SelectedRows path); mixed densifies
    if _is_selected_rows(a) and _is_selected_rows(b):
        return a.append(b)
    if _is_selected_rows(a):
        a = a._data
    if _is_selected_rows(b):
        b = b._data
    return a + b


def _accumulate_leaf(t, g):
    from .tensor import Tensor
    if _is_selected_rows(g):
        if t.grad is None:
            t.grad = g
            return
        if _is_selected_rows(t.grad):
            t.grad = t.grad.append(g)
            return
        g = g._data  # mixed: fall through to dense accumulation
    g = jnp.asarray(g, t._data.dtype)
    if t.grad is None:
        t.grad = Tensor(g, stop_gradient=True)
    else:
        t.grad = Tensor(t.grad._data + g, stop_gradient=True)
