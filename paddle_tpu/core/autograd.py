"""Eager reverse-mode autodiff engine.

Reference parity: the dygraph tape + BasicEngine
(``paddle/fluid/imperative/tracer.cc:132`` records grad nodes;
``basic_engine.cc:39,221,265`` executes them;
``gradient_accumulator.cc`` sums incoming grads).

TPU-native design: instead of per-op registered grad kernels, every traced op
captures a ``jax.vjp`` closure at forward time.  ``backward()`` walks nodes in
reverse creation order (a valid topological order for an eagerly-built tape)
and accumulates cotangents.  The jit/static path does NOT use this tape — it
uses ``jax.grad`` over a functional step (see paddle_tpu.jit / hapi), which is
where performance comes from; this engine exists for eager ergonomics parity.
"""
from __future__ import annotations

import contextlib
import threading
import weakref

import jax
import jax.numpy as jnp

_state = threading.local()


def _tls():
    if not hasattr(_state, "enabled"):
        _state.enabled = True
    return _state


def grad_enabled() -> bool:
    return _tls().enabled


@contextlib.contextmanager
def no_grad():
    """paddle.no_grad — disable tape recording."""
    tls = _tls()
    prev = tls.enabled
    tls.enabled = False
    try:
        yield
    finally:
        tls.enabled = prev


@contextlib.contextmanager
def enable_grad():
    tls = _tls()
    prev = tls.enabled
    tls.enabled = True
    try:
        yield
    finally:
        tls.enabled = prev


_node_counter = [0]


class GradNode:
    """One recorded op: inputs that require grad + the vjp closure.

    Holds STRONG refs to differentiable input tensors (keeps the upstream
    graph alive) and WEAK refs to outputs (so dead branches are collectable).
    """

    __slots__ = ("id", "inputs", "out_refs", "out_meta", "vjp_fn", "name",
                 "__weakref__")

    def __init__(self, inputs, outputs, vjp_fn, name=""):
        _node_counter[0] += 1
        self.id = _node_counter[0]
        self.inputs = inputs                      # list[Tensor]
        self.out_refs = [weakref.ref(o) for o in outputs]
        self.out_meta = [(o.shape, o._data.dtype) for o in outputs]
        self.vjp_fn = vjp_fn                      # cotangents tuple -> input grads
        self.name = name

    def outputs_alive(self):
        return [r() for r in self.out_refs]


def snapshot_for_inplace(t):
    """Freeze `t`'s current graph identity into a fresh Tensor so an
    in-place op can rebuild `t` on top of it.  The producing node's weak
    output ref is re-pointed at the snapshot, keeping the upstream chain
    intact after `t` is mutated."""
    from .tensor import Tensor
    old = Tensor(t._data, stop_gradient=t.stop_gradient)
    node = t._grad_node
    old._grad_node = node
    old._retain_grad = t._retain_grad
    if node is not None:
        for i, ref in enumerate(node.out_refs):
            if ref() is t:
                node.out_refs[i] = weakref.ref(old)
    return old


def adopt_result(target, out):
    """Make `target` take over `out`'s value AND its place in the graph
    (used by in-place ops: reshape_, __setitem__).  Rebinds the producing
    node's weak output ref so backward seeds reach it.  The op producing
    `out` must have consumed ``snapshot_for_inplace(target)``, NOT target
    itself, or the upstream chain is lost."""
    node = out._grad_node
    target._data = out._data
    target._grad_node = node
    target.stop_gradient = out.stop_gradient
    if node is not None:
        for i, ref in enumerate(node.out_refs):
            if ref() is out:
                node.out_refs[i] = weakref.ref(target)
    return target


def run_inplace(target, op, *args, **kwargs):
    """Execute ``op`` as the in-place realization of ``target``."""
    old = snapshot_for_inplace(target)
    out = op(old, *args, **kwargs)
    return adopt_result(target, out)


def record(inputs, outputs, vjp_fn, name=""):
    """Attach a GradNode to output tensors (called by the op dispatcher)."""
    node = GradNode(inputs, outputs, vjp_fn, name)
    for o in outputs:
        o._grad_node = node
        o.stop_gradient = False
    return node


def _collect_nodes(root_nodes):
    """All nodes reachable from the roots, sorted by creation id descending."""
    seen = {}
    stack = list(root_nodes)
    while stack:
        node = stack.pop()
        if node is None or node.id in seen:
            continue
        seen[node.id] = node
        for t in node.inputs:
            if t._grad_node is not None:
                stack.append(t._grad_node)
    return sorted(seen.values(), key=lambda n: -n.id)


def backward(tensors, grad_tensors=None, retain_graph=False):
    """Run reverse mode from `tensors` (reference: basic_engine.cc:265).

    Leaf tensors (stop_gradient=False, no grad node) receive ``.grad``.
    Non-leaf tensors receive ``.grad`` only if ``retain_grads()`` was called.
    """
    from .tensor import Tensor

    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    # cotangent store keyed by id(tensor); tensors kept alive by node refs
    grads: dict[int, jax.Array] = {}
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "backward() on a non-scalar tensor requires explicit "
                    "grad_tensors (got shape %s)" % (t.shape,))
            g_arr = jnp.ones(t.shape, t._data.dtype)
        else:
            g_arr = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        grads[id(t)] = grads.get(id(t), 0) + g_arr

    roots = [t._grad_node for t in tensors if t._grad_node is not None]
    # seed leaves passed directly
    for t in tensors:
        if t._grad_node is None and not t.stop_gradient:
            _accumulate_leaf(t, grads[id(t)])

    for node in _collect_nodes(roots):
        outs = node.outputs_alive()
        cotangents = []
        any_seed = False
        for ref, (shape, dtype) in zip(outs, node.out_meta):
            g = grads.pop(id(ref), None) if ref is not None else None
            if g is None:
                cotangents.append(jnp.zeros(shape, dtype))
            else:
                any_seed = True
                cotangents.append(jnp.asarray(g, dtype))
        if not any_seed:
            continue
        ct = tuple(cotangents) if len(cotangents) > 1 else cotangents[0]
        in_grads = node.vjp_fn(ct)
        if not isinstance(in_grads, tuple):
            in_grads = (in_grads,)
        for t, g in zip(node.inputs, in_grads):
            if g is None:
                continue
            if t._grad_node is None:
                _accumulate_leaf(t, g)
            else:
                grads[id(t)] = _sum(grads.get(id(t)), g)
                if t._retain_grad:
                    _accumulate_leaf(t, g)
        if not retain_graph:
            # keep the node (so a second backward raises via _freed_vjp)
            # but drop the closure and its forward residuals
            node.vjp_fn = _freed_vjp


def _freed_vjp(*_):
    raise RuntimeError(
        "Trying to backward through the graph a second time; "
        "pass retain_graph=True to backward() if needed.")


def _sum(a, b):
    return b if a is None else a + b


def _accumulate_leaf(t, g):
    from .tensor import Tensor
    g = jnp.asarray(g, t._data.dtype)
    if t.grad is None:
        t.grad = Tensor(g, stop_gradient=True)
    else:
        t.grad = Tensor(t.grad._data + g, stop_gradient=True)
