"""Global flag registry.

Reference parity: gflags in ``paddle/fluid/platform/flags.cc:33-539`` plus
the getter/setter bridge ``pybind/global_value_getter_setter.cc``.  Flags are
settable via ``paddle_tpu.set_flags`` or environment ``FLAGS_*`` at import.
"""
from __future__ import annotations

import os

_REGISTRY: dict[str, dict] = {}


def define_flag(name: str, default, doc: str = ""):
    env = os.environ.get("FLAGS_" + name)
    value = default
    if env is not None:
        if isinstance(default, bool):
            value = env.lower() in ("1", "true", "yes")
        elif isinstance(default, int):
            value = int(env)
        elif isinstance(default, float):
            value = float(env)
        else:
            value = env
    _REGISTRY[name] = {"value": value, "default": default, "doc": doc}


def set_flags(flags: dict):
    """paddle.set_flags({'FLAGS_check_nan_inf': True})"""
    for k, v in flags.items():
        name = k[6:] if k.startswith("FLAGS_") else k
        if name not in _REGISTRY:
            raise KeyError("unknown flag %r" % k)
        _REGISTRY[name]["value"] = v


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        name = k[6:] if k.startswith("FLAGS_") else k
        out["FLAGS_" + name] = _REGISTRY[name]["value"]
    return out


def flag(name: str):
    return _REGISTRY[name]["value"]


# Core flags (TPU-meaningful subset of reference platform/flags.cc)
define_flag("check_nan_inf", False,
            "After every eager op, scan outputs for NaN/Inf and raise "
            "(reference flags.cc:44 + nan_inf_utils_detail.cc).")
define_flag("sort_sum_gradient", False,
            "Deterministic gradient accumulation order "
            "(reference flags.cc:527).")
define_flag("eager_delete_tensor_gb", 0.0,
            "GC threshold; a no-op under XLA memory management.")
define_flag("allocator_strategy", "xla",
            "Informational: XLA owns HBM allocation on TPU.")
define_flag("use_bf16_matmul", True,
            "Allow bf16 accumulation hints for matmul on MXU.")
define_flag("tpu_deterministic", False,
            "Force deterministic XLA reductions where available "
            "(reference: FLAGS_cudnn_deterministic flags.cc:98).")
define_flag("log_level", 0, "VLOG-style verbosity for paddle_tpu internals.")
