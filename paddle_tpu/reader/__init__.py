"""paddle.reader — generator-composition utilities for 1.x data code.

Reference parity: ``python/paddle/reader/decorator.py`` (cache,
map_readers, shuffle, chain, compose, buffered, firstn, xmap_readers,
multiprocess_reader).  These are pure-Python reader combinators; the
modern path is ``paddle.io.DataLoader`` (process workers + device
prefetch), but 1.x scripts compose readers with these decorators and
feed them through ``paddle.batch`` / ``DataFeeder``.
"""
from __future__ import annotations

import itertools
import queue as queue_mod
import random
import threading

__all__ = [
    "cache", "map_readers", "buffered", "compose", "chain", "shuffle",
    "firstn", "xmap_readers", "multiprocess_reader",
]


def cache(reader):
    """Cache the wrapped reader's full output in memory on first read."""
    all_data = tuple(reader())

    def cached_reader():
        return iter(all_data)

    return cached_reader


def map_readers(func, *readers):
    """Yield func(*items) over readers zipped together."""

    def reader():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield func(*items)

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle: fill ``buf_size`` samples, emit shuffled."""

    def shuffled_reader():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            random.shuffle(buf)
            yield from buf

    return shuffled_reader


def chain(*readers):
    """Concatenate readers back to back."""

    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers, **kwargs):
    """Zip readers into flattened tuples; check_alignment (default True)
    raises if they end at different lengths (reference ComposeNotAligned)."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for items in zip(*rs):
                yield sum((make_tuple(i) for i in items), ())
            return
        for items in itertools.zip_longest(*rs):
            if any(i is None for i in items):
                raise ComposeNotAligned(
                    "outputs of readers are not aligned")
            yield sum((make_tuple(i) for i in items), ())

    return reader


class ComposeNotAligned(ValueError):
    pass


def buffered(reader, size):
    """Read ahead up to ``size`` items on a daemon thread."""

    class _End:
        pass

    class _Err:
        def __init__(self, e):
            self.e = e

    def buffered_reader():
        q = queue_mod.Queue(maxsize=size)

        def fill():
            try:
                for item in reader():
                    q.put(item)
            except Exception as e:  # surface in the consumer, not the
                q.put(_Err(e))      # daemon thread (silent truncation)
                return
            q.put(_End)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is _End:
                return
            if isinstance(item, _Err):
                raise item.e
            yield item

    return buffered_reader


def firstn(reader, n):
    """Only the first ``n`` items."""

    def firstn_reader():
        return itertools.islice(reader(), n)

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with ``process_num`` worker THREADS
    (the reference also uses threads here despite the name), optionally
    order-preserving."""

    def xreader():
        in_q = queue_mod.Queue(buffer_size)
        out_q = queue_mod.Queue(buffer_size)
        END = object()

        ERR = []

        def feed():
            try:
                for i, item in enumerate(reader()):
                    in_q.put((i, item))
            except Exception as e:
                ERR.append(e)
            for _ in range(process_num):
                in_q.put(END)

        def work():
            while True:
                job = in_q.get()
                if job is END:
                    out_q.put(END)
                    return
                i, item = job
                try:
                    out_q.put((i, mapper(item)))
                except Exception as e:
                    ERR.append(e)
                    out_q.put(END)
                    return

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()
        finished = 0
        if not order:
            while finished < process_num:
                res = out_q.get()
                if res is END:
                    finished += 1
                    continue
                yield res[1]
            if ERR:
                raise ERR[0]
            return
        pending = {}
        next_i = 0
        # drain until every worker ENDed — never block on results a dead
        # worker can no longer produce
        while finished < process_num:
            res = out_q.get()
            if res is END:
                finished += 1
                continue
            pending[res[0]] = res[1]
            while next_i in pending:
                yield pending.pop(next_i)
                next_i += 1
        while next_i in pending:
            yield pending.pop(next_i)
            next_i += 1
        if ERR:
            raise ERR[0]

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave several readers concurrently (thread-backed here: the
    payloads are numpy batches that the GIL releases on copy; the modern
    process path is paddle.io.DataLoader's worker pool)."""

    def merged_reader():
        q = queue_mod.Queue(queue_size)
        END = object()

        errors = []

        def pump(r):
            try:
                for item in r():
                    q.put(item)
            except Exception as e:
                errors.append(e)
            q.put(END)

        for r in readers:
            threading.Thread(target=pump, args=(r,), daemon=True).start()
        finished = 0
        while finished < len(readers):
            item = q.get()
            if item is END:
                finished += 1
                continue
            yield item
        if errors:
            raise errors[0]

    return merged_reader
