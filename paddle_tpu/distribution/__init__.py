"""Probability distributions.

Reference parity: ``python/paddle/distribution.py`` — ``Distribution`` base,
``Normal``, ``Uniform``, ``Categorical`` with sample / entropy / log_prob /
probs / kl_divergence.  TPU-native design: parameters are framework Tensors
and every method is built from tape-aware primitives (``core.dispatch``), so
``log_prob(...).backward()`` flows gradients into the parameters — the eager
REINFORCE / MLE loops users write against the reference work unchanged.
Sampling draws from the framework RNG (``paddle_tpu.core.rng``) so
``paddle.seed`` controls reproducibility; ``Normal.rsample`` is
reparameterized (differentiable through loc/scale).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import primitive, ensure_tensor
from ..core import rng as rng_mod

_LOG_2PI = math.log(2 * math.pi)


def _sample_key(seed):
    """seed=0 → framework RNG (paddle.seed-controlled); nonzero → that seed,
    reproducible independently of global state (reference sample(shape, seed)
    semantics)."""
    if seed:
        return jax.random.key(seed)
    return rng_mod.next_key()


# ---- tape-aware kernels -------------------------------------------------
_normal_log_prob = primitive(name="normal_log_prob")(
    lambda loc, scale, value: -((value - loc) ** 2) / (2 * scale ** 2)
    - jnp.log(scale) - 0.5 * _LOG_2PI)

_normal_entropy = primitive(name="normal_entropy")(
    lambda loc, scale: jnp.broadcast_to(
        0.5 + 0.5 * _LOG_2PI + jnp.log(scale),
        jnp.broadcast_shapes(loc.shape, scale.shape)))

_normal_kl = primitive(name="normal_kl")(
    lambda loc1, scale1, loc2, scale2: 0.5 * (
        (scale1 / scale2) ** 2 + ((loc1 - loc2) / scale2) ** 2
        - 1.0 - 2.0 * jnp.log(scale1 / scale2)))

_normal_rsample = primitive(name="normal_rsample", nondiff=(2,))(
    lambda loc, scale, eps: loc + scale * eps)

_uniform_log_prob = primitive(name="uniform_log_prob")(
    lambda low, high, value: jnp.where(
        (value > low) & (value < high),  # strict bounds (reference parity)
        -jnp.log(high - low),
        -jnp.inf))

_uniform_entropy = primitive(name="uniform_entropy")(
    lambda low, high: jnp.log(high - low))


# Reference-parity quirk (distribution.py Categorical): sample/probs/
# log_prob treat `logits` as unnormalized probability WEIGHTS (linear
# normalization, probs = logits/sum(logits), multinomial sampling), while
# entropy/kl_divergence use softmax(logits).  Both are kept as-is so ported
# code sees identical numbers.
def _cat_log_prob_fn(logits, value):
    prob = logits / jnp.sum(logits, axis=-1, keepdims=True)
    log_p = jnp.log(prob)
    log_p = jnp.broadcast_to(log_p, value.shape + log_p.shape[-1:])
    idx = value.astype(jnp.int32)[..., None]
    return jnp.take_along_axis(log_p, idx, axis=-1)[..., 0]


_cat_log_prob = primitive(name="categorical_log_prob", nondiff=(1,))(
    _cat_log_prob_fn)


def _cat_entropy_fn(logits):
    log_p = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.sum(jnp.exp(log_p) * log_p, axis=-1)


_cat_entropy = primitive(name="categorical_entropy")(_cat_entropy_fn)


def _cat_kl_fn(logits1, logits2):
    lp, lq = (jax.nn.log_softmax(l, axis=-1) for l in (logits1, logits2))
    return jnp.sum(jnp.exp(lp) * (lp - lq), axis=-1)


_cat_kl = primitive(name="categorical_kl")(_cat_kl_fn)

_exp = primitive(name="distribution_exp")(jnp.exp)


class Distribution:
    """Base class (reference: distribution.py Distribution)."""

    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        return _exp(self.log_prob(value))

    def kl_divergence(self, other):
        raise NotImplementedError


class Normal(Distribution):
    """Normal(loc, scale) — reference distribution.py Normal."""

    def __init__(self, loc, scale, name=None):
        self.loc = ensure_tensor(loc, dtype="float32")
        self.scale = ensure_tensor(scale, dtype="float32")
        self.name = name

    def _base_shape(self):
        return jnp.broadcast_shapes(tuple(self.loc._data.shape),
                                    tuple(self.scale._data.shape))

    def sample(self, shape=(), seed=0):
        eps = jax.random.normal(_sample_key(seed),
                                tuple(shape) + self._base_shape(),
                                dtype=self.loc._data.dtype)
        out = self.loc._data + self.scale._data * eps
        return Tensor(out)

    def rsample(self, shape=(), seed=0):
        """Reparameterized sample — gradients flow into loc/scale."""
        eps = jax.random.normal(_sample_key(seed),
                                tuple(shape) + self._base_shape(),
                                dtype=self.loc._data.dtype)
        return _normal_rsample(self.loc, self.scale, Tensor(eps))

    def entropy(self):
        return _normal_entropy(self.loc, self.scale)

    def log_prob(self, value):
        return _normal_log_prob(self.loc, self.scale,
                                ensure_tensor(value, dtype="float32"))

    def kl_divergence(self, other):
        """KL(self || other) between two Normals."""
        return _normal_kl(self.loc, self.scale, other.loc, other.scale)


class Uniform(Distribution):
    """Uniform(low, high) — reference distribution.py Uniform."""

    def __init__(self, low, high, name=None):
        self.low = ensure_tensor(low, dtype="float32")
        self.high = ensure_tensor(high, dtype="float32")
        self.name = name

    def sample(self, shape=(), seed=0):
        base = jnp.broadcast_shapes(tuple(self.low._data.shape),
                                    tuple(self.high._data.shape))
        u = jax.random.uniform(_sample_key(seed), tuple(shape) + base,
                               dtype=self.low._data.dtype)
        return Tensor(self.low._data
                      + (self.high._data - self.low._data) * u)

    def entropy(self):
        return _uniform_entropy(self.low, self.high)

    def log_prob(self, value):
        return _uniform_log_prob(self.low, self.high,
                                 ensure_tensor(value, dtype="float32"))


class Categorical(Distribution):
    """Categorical(logits) — reference distribution.py Categorical."""

    def __init__(self, logits, name=None):
        self.logits = ensure_tensor(logits, dtype="float32")
        self.name = name

    def sample(self, shape=(), seed=0):
        # multinomial over linearly-normalized weights (reference parity)
        weights = self.logits._data
        log_w = jnp.log(weights / jnp.sum(weights, axis=-1, keepdims=True))
        return Tensor(jax.random.categorical(
            _sample_key(seed), log_w, axis=-1,
            shape=tuple(shape) + weights.shape[:-1]))

    def entropy(self):
        return _cat_entropy(self.logits)

    def log_prob(self, value):
        return _cat_log_prob(self.logits, ensure_tensor(value))

    def kl_divergence(self, other):
        return _cat_kl(self.logits, other.logits)


def kl_divergence(p: Distribution, q: Distribution):
    """paddle.distribution.kl_divergence(p, q)."""
    return p.kl_divergence(q)


class MultivariateNormalDiag:
    """reference: distribution.py MultivariateNormalDiag (loc + diagonal
    scale)."""

    def __init__(self, loc, scale):
        from ..core.dispatch import ensure_tensor
        self.loc = ensure_tensor(loc)
        self.scale = ensure_tensor(scale)  # diagonal entries [..., D, D]

    def _diag(self):
        import jax.numpy as jnp
        return jnp.diagonal(self.scale._data, axis1=-2, axis2=-1)

    def sample(self, shape=()):
        import jax.numpy as jnp
        from ..core import rng as rng_mod
        from ..core.tensor import Tensor
        import jax
        d = self._diag()
        eps = jax.random.normal(
            rng_mod.next_key(), tuple(shape) + self.loc._data.shape)
        return Tensor(self.loc._data + eps * d)

    def entropy(self):
        import jax.numpy as jnp
        from ..core.tensor import Tensor
        import math
        d = self._diag()
        k = d.shape[-1]
        return Tensor(0.5 * k * (1.0 + math.log(2 * math.pi))
                      + jnp.sum(jnp.log(d), axis=-1))

    def log_prob(self, value):
        import jax.numpy as jnp
        from ..core.dispatch import ensure_tensor
        from ..core.tensor import Tensor
        import math
        v = ensure_tensor(value)._data
        d = self._diag()
        z = (v - self.loc._data) / d
        k = d.shape[-1]
        return Tensor(-0.5 * jnp.sum(z * z, -1)
                      - jnp.sum(jnp.log(d), -1)
                      - 0.5 * k * math.log(2 * math.pi))

    def kl_divergence(self, other):
        import jax.numpy as jnp
        from ..core.tensor import Tensor
        d1, d2 = self._diag(), other._diag()
        mu = self.loc._data - other.loc._data
        return Tensor(0.5 * jnp.sum(
            (d1 / d2) ** 2 + (mu / d2) ** 2 - 1
            + 2 * (jnp.log(d2) - jnp.log(d1)), axis=-1))
