"""``fluid.optimizer`` — the 1.x optimizer surface.

Reference parity: ``python/paddle/fluid/optimizer.py`` — the *Optimizer
class names taking ``parameter_list`` (2.0 renamed it ``parameters``) and
``regularization`` (→ ``weight_decay``), plus the utilities that file
hosts (EMA, ModelAverage, Lookahead, Recompute, Pipeline).
"""
from __future__ import annotations

import logging

from ..optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, Adam, AdamW, Adamax, Adadelta, Adagrad,
    RMSProp, Lamb, LarsMomentum, LRScheduler)
from ..optimizer import lr  # noqa: F401
from ..optimizer.extras import (  # noqa: F401
    DecayedAdagrad, Ftrl, Dpsgd, ExponentialMovingAverage, ModelAverage,
    LookaheadOptimizer)

_LOG = logging.getLogger("paddle_tpu.fluid")


def _compat(cls):
    """Wrap a 2.0 optimizer class with the 1.x kwarg names."""

    class Compat(cls):
        def __init__(self, *args, **kwargs):
            if "parameter_list" in kwargs:
                kwargs["parameters"] = kwargs.pop("parameter_list")
            if "regularization" in kwargs:
                kwargs["weight_decay"] = kwargs.pop("regularization")
            super().__init__(*args, **kwargs)

    Compat.__name__ = cls.__name__ + "Optimizer"
    Compat.__qualname__ = Compat.__name__
    Compat.__doc__ = (f"1.x alias of paddle.optimizer.{cls.__name__} "
                      "(parameter_list/regularization kwargs)")
    return Compat


SGDOptimizer = _compat(SGD)
MomentumOptimizer = _compat(Momentum)
AdagradOptimizer = _compat(Adagrad)
AdamOptimizer = _compat(Adam)
AdamaxOptimizer = _compat(Adamax)
AdadeltaOptimizer = _compat(Adadelta)
RMSPropOptimizer = _compat(RMSProp)
LambOptimizer = _compat(Lamb)
LarsMomentumOptimizer = _compat(LarsMomentum)
DecayedAdagradOptimizer = _compat(DecayedAdagrad)
FtrlOptimizer = _compat(Ftrl)
DpsgdOptimizer = _compat(Dpsgd)


class RecomputeOptimizer:
    """reference: fluid/optimizer.py RecomputeOptimizer — rebuilt the
    backward pass re-forwarding checkpoint segments.  Rematerialization is
    a transform here (``fleet.utils.recompute`` / ``jax.checkpoint`` on
    the segment), so this wrapper keeps the API and delegates the actual
    optimization to the inner optimizer."""

    def __init__(self, optimizer):
        self.inner_optimizer = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints
        _LOG.info(
            "RecomputeOptimizer: wrap the checkpointed segments with "
            "paddle.distributed.fleet.utils.recompute (jax.checkpoint) — "
            "the backward rewrite is a transform, not a program pass")

    def __getattr__(self, name):
        return getattr(self.inner_optimizer, name)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self.inner_optimizer.minimize(loss)


class PipelineOptimizer:
    """reference: fluid/optimizer.py:3718 PipelineOptimizer (GPipe
    sections over device_guard programs).  The SPMD engine lives in
    ``paddle_tpu.parallel.pipeline`` (PipelineLayer + TrainStep); this
    wrapper keeps 1.x scripts importable and optimizes un-pipelined when
    invoked directly."""

    def __init__(self, optimizer, num_microbatches=1, start_cpu_core_id=0):
        self.inner_optimizer = optimizer
        self.num_microbatches = num_microbatches

    def __getattr__(self, name):
        return getattr(self.inner_optimizer, name)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        _LOG.warning(
            "PipelineOptimizer.minimize: running un-pipelined — build the "
            "model as fleet.meta_parallel.PipelineLayer and train through "
            "TrainStep for the SPMD pipeline schedule")
        return self.inner_optimizer.minimize(loss)
