"""``fluid.layers`` — the 1.x layer/op namespace.

Reference parity: ``python/paddle/fluid/layers/`` (nn.py, tensor.py,
control_flow.py, detection.py…), the surface 1.x model code builds on.
Everything maps to the modern ops; graph building works because the ops
record into the default Program under ``paddle.enable_static()``.
"""
from __future__ import annotations

# graph-building layers (create parameters)
from ..static.nn import (  # noqa: F401
    fc, conv2d, batch_norm, embedding, dropout,
    cond, while_loop, case, switch_case, py_func, multi_box_head)

# tensor ops under their fluid names
from ..ops.compat_ops import (  # noqa: F401
    fill_constant, create_global_var, create_parameter, elementwise_add,
    elementwise_sub, elementwise_mul, elementwise_div, elementwise_pow,
    elementwise_mod, elementwise_floordiv, elementwise_max,
    elementwise_min, reduce_sum, reduce_mean, reduce_max, reduce_min,
    reduce_prod, has_inf, has_nan, shape, slice, strided_slice,
    crop_tensor, unstack, create_array, array_write, array_read,
    array_length)
from ..ops.math import (  # noqa: F401
    abs, exp, log, sqrt, square, sin, cos, tanh, sigmoid, clip, scale,
    cumsum, pow, matmul)
from ..ops.creation import (  # noqa: F401
    zeros, ones, full, arange, linspace, assign)
from ..ops.manipulation import (  # noqa: F401
    concat, split, reshape, transpose, squeeze, unsqueeze, stack,
    gather, gather_nd, scatter, expand_as, cast, one_hot, topk, argsort,
    where)
from ..nn.functional import (  # noqa: F401
    relu, softmax, cross_entropy, log_softmax, pad, pool2d,
    image_resize, grid_sample, bilinear_tensor_product, dice_loss,
    linear_chain_crf)
from ..nn.functional.loss import (  # noqa: F401
    square_error_cost, softmax_with_cross_entropy)
from ..static.compat import accuracy, auc, Print  # noqa: F401
from ..vision.ops import (  # noqa: F401
    yolo_box, prior_box, box_coder, multiclass_nms, roi_align, roi_pool)

# sequence layers
from ..nn.functional.sequence import (  # noqa: F401
    sequence_pad, sequence_unpad, sequence_pool, sequence_softmax,
    sequence_expand, sequence_reverse)


def mean(x, name=None):
    from ..ops.math import mean as _mean
    return _mean(x)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    """reference mul_op.cc: flatten x after x_num_col_dims and y after
    y_num_col_dims, 2-D matmul, restore the leading dims."""
    import numpy as _np
    from ..ops.math import matmul as _matmul
    from ..ops.manipulation import reshape as _reshape
    x_lead = list(x.shape[:x_num_col_dims])
    x_flat = _reshape(x, [int(_np.prod(x_lead) or 1), -1])
    y_tail = list(y.shape[y_num_col_dims:])
    y_flat = _reshape(y, [-1, int(_np.prod(y_tail) or 1)])
    out = _matmul(x_flat, y_flat)
    return _reshape(out, x_lead + y_tail)


def data(name, shape, dtype="float32", lod_level=0,
         append_batch_size=True):
    """fluid.layers.data prepends the batch dim when append_batch_size
    (1.x convention); dynamic dims are rejected on TPU — declare the
    batch size explicitly."""
    from ..static.program import data as _data
    if append_batch_size:
        raise ValueError(
            "fluid.layers.data(append_batch_size=True) implies a dynamic "
            "batch dim, unsupported on the TPU backend; pass the full "
            "shape and append_batch_size=False")
    return _data(name, shape, dtype)


# -- name-resolution chain -------------------------------------------------
# fluid.layers at v1.x exported ~290 symbols, most of which live on in the
# 2.0 surface under paddle.* / paddle.nn.functional / static.nn /
# vision.ops.  Rather than enumerate every alias, resolve through the same
# chain the reference's DEFINE_ALIAS machinery flattened.
def __getattr__(name):
    import paddle_tpu as _p
    from ..nn import functional as _F
    from ..static import nn as _snn
    from ..vision import ops as _vops
    from ..ops import compat_ops as _compat
    from .. import nn as _nn
    for src in (_F, _snn, _vops, _compat, _p, _nn):
        if hasattr(src, name):
            return getattr(src, name)
    # control-flow / decode classes kept under their 2.0 homes
    from ..nn import decode as _decode
    if hasattr(_decode, name):
        return getattr(_decode, name)
    raise AttributeError(
        f"module 'paddle.fluid.layers' has no attribute '{name}'")


# -- 1.x-convention wrappers (names with no 2.0 twin) ---------------------
import builtins as _builtins


def range(start, end, step, dtype, name=None):  # noqa: A001
    import paddle_tpu as _p
    return _p.arange(start, end, step, dtype)


def reverse(x, axis, name=None):
    import paddle_tpu as _p
    return _p.flip(x, axis)


def size(input, name=None):
    import paddle_tpu as _p
    return _p.numel(input)


def sums(input, out=None):
    import paddle_tpu as _p
    res = _p.add_n(list(input))
    if out is not None:
        from ..core import autograd
        autograd.adopt_result(out, res)
        return out
    return res


def create_tensor(dtype, name=None, persistable=False):
    """1.x assign-target creation — a zero scalar of the dtype."""
    import paddle_tpu as _p
    return _p.zeros([1], dtype)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,
                   name=None):
    import paddle_tpu as _p
    return _p.uniform(shape, dtype, min=min, max=max, seed=seed)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    name=None):
    import paddle_tpu as _p
    return _p.normal(mean=mean, std=std, shape=shape).astype(dtype)


def _batch_size_like(fn, input, shape, input_dim_idx=0, output_dim_idx=0):
    shape = list(shape)
    shape[output_dim_idx] = int(input.shape[input_dim_idx])
    return fn(shape)


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0,
                                  force_cpu=False):
    import paddle_tpu as _p
    return _batch_size_like(lambda s: _p.full(s, value, dtype), input,
                            shape, input_dim_idx, output_dim_idx)


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    import paddle_tpu as _p
    return _batch_size_like(
        lambda s: _p.uniform(s, dtype, min=min, max=max, seed=seed),
        input, shape, input_dim_idx, output_dim_idx)


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    import paddle_tpu as _p
    return _batch_size_like(
        lambda s: _p.normal(mean=mean, std=std, shape=s).astype(dtype),
        input, shape, input_dim_idx, output_dim_idx)


def reduce_all(input, dim=None, keep_dim=False, name=None):
    import paddle_tpu as _p
    return _p.all(input, axis=dim, keepdim=keep_dim)


def reduce_any(input, dim=None, keep_dim=False, name=None):
    import paddle_tpu as _p
    return _p.any(input, axis=dim, keepdim=keep_dim)


def unique_with_counts(x, dtype="int32"):
    import paddle_tpu as _p
    out, index, counts = _p.unique(x, return_inverse=True,
                                   return_counts=True)
    return out, index.astype(dtype), counts.astype(dtype)


def crop(x, shape=None, offsets=None, name=None):
    import paddle_tpu as _p
    return _p.crop_tensor(x, shape=shape, offsets=offsets)


def resize_linear(input, out_shape=None, scale=None, name=None,
                  align_corners=True, align_mode=1, data_format="NCW"):
    from ..nn.functional.common import interpolate
    return interpolate(input, size=out_shape, scale_factor=scale,
                       mode="linear", align_corners=align_corners,
                       align_mode=align_mode, data_format=data_format)


def grid_sampler(x, grid, name=None):
    from ..nn.functional import grid_sample
    return grid_sample(x, grid)


def adaptive_pool2d(input, pool_size, pool_type="max",
                    require_index=False, name=None):
    from ..nn import functional as _F
    if pool_type == "max":
        return _F.adaptive_max_pool2d(input, pool_size,
                                      return_mask=require_index)
    return _F.adaptive_avg_pool2d(input, pool_size)


def adaptive_pool3d(input, pool_size, pool_type="max",
                    require_index=False, name=None):
    from ..nn import functional as _F
    if pool_type == "max":
        return _F.adaptive_max_pool3d(input, pool_size,
                                      return_mask=require_index)
    return _F.adaptive_avg_pool3d(input, pool_size)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    from ..nn.functional import normalize
    return normalize(x, p=2, axis=axis, epsilon=epsilon)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,
        data_format="NCHW"):
    from ..nn.functional import local_response_norm
    # both lrn_op.cc and this backend's local_response_norm apply alpha to
    # the raw window sum — pass it through unchanged
    return local_response_norm(input, size=n, alpha=alpha, beta=beta,
                               k=k, data_format=data_format)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    import paddle_tpu as _p
    return _p.clip(x, t_min, t_max)


def hard_shrink(x, threshold=0.5):
    from ..nn.functional import hardshrink
    return hardshrink(x, threshold)


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    import paddle_tpu as _p
    # fluid's parametric form (2.0 fixes slope=1/6, offset=0.5)
    return _p.clip(slope * x + offset, 0.0, 1.0)


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    import paddle_tpu as _p
    return x * _p.clip(x + offset, 0.0, threshold) / scale


def clip_by_norm(x, max_norm, name=None):
    from ..core.dispatch import primitive, ensure_tensor
    import jax.numpy as jnp
    x = ensure_tensor(x)

    def fn(a):
        norm = jnp.sqrt(jnp.sum(a * a))
        return a * (max_norm / jnp.maximum(norm, max_norm))

    return primitive(name="clip_by_norm")(fn)(x)


def kldiv_loss(x, target, reduction="mean", name=None):
    from ..nn.functional import kl_div
    return kl_div(x, target, reduction=reduction)


def huber_loss(input, label, delta):
    """reference huber_loss_op.cc: elementwise huber, [N, 1] outputs."""
    from ..core.dispatch import primitive, ensure_tensor
    import jax.numpy as jnp
    input, label = ensure_tensor(input), ensure_tensor(label)
    d = float(delta)

    def fn(x, y):
        r = jnp.abs(y - x)
        return jnp.where(r <= d, 0.5 * r * r, d * (r - 0.5 * d))

    return primitive(name="huber_loss")(fn)(input, label)


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    """reference margin_rank_loss_op.cc: max(0, -label*(left-right)+m)."""
    from ..core.dispatch import primitive, ensure_tensor
    import jax.numpy as jnp
    label = ensure_tensor(label)
    left, right = ensure_tensor(left), ensure_tensor(right)

    def fn(lab, lf, rt):
        return jnp.maximum(0.0, -lab * (lf - rt) + margin)

    return primitive(name="margin_rank_loss")(fn)(label, left, right)


def rank_loss(label, left, right, name=None):
    """reference rank_loss_op.cc: sigmoid-CE on o = left - right with
    soft label P."""
    from ..core.dispatch import primitive, ensure_tensor
    import jax
    import jax.numpy as jnp
    label = ensure_tensor(label)
    left, right = ensure_tensor(left), ensure_tensor(right)

    def fn(p, lf, rt):
        o = lf - rt
        return jax.nn.softplus(o) - p * o

    return primitive(name="rank_loss")(fn)(label, left, right)


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      name=None, normalize=False):
    """reference sigmoid_cross_entropy_with_logits_op.cc (elementwise,
    ignore_index masking, optional normalize-by-valid-count)."""
    from ..core.dispatch import primitive, ensure_tensor
    import jax
    import jax.numpy as jnp
    x, label = ensure_tensor(x), ensure_tensor(label)

    def fn(z, t):
        per = jax.nn.softplus(z) - t * z  # = max(z,0)-z*t+log(1+e^-|z|)
        valid = t != ignore_index
        per = jnp.where(valid, per, 0.0)
        if normalize:
            per = per / jnp.maximum(valid.sum().astype(per.dtype), 1.0)
        return per

    return primitive(name="sigmoid_cross_entropy_with_logits")(fn)(x, label)


def cos_sim(X, Y):
    from ..nn.functional import cosine_similarity
    import paddle_tpu as _p
    return _p.unsqueeze(cosine_similarity(X, Y, axis=1), [1])


def mean_iou(input, label, num_classes):
    """reference mean_iou_op.cc: (mean_iou, out_wrong, out_correct)."""
    from ..core.dispatch import primitive, ensure_tensor
    import jax.numpy as jnp
    input, label = ensure_tensor(input), ensure_tensor(label)
    nc = int(num_classes)

    def fn(pred, lab):
        pred = pred.reshape(-1).astype(jnp.int32)
        lab = lab.reshape(-1).astype(jnp.int32)
        correct = jnp.zeros((nc,), jnp.int32).at[lab].add(
            (pred == lab).astype(jnp.int32))
        pred_cnt = jnp.zeros((nc,), jnp.int32).at[pred].add(1)
        lab_cnt = jnp.zeros((nc,), jnp.int32).at[lab].add(1)
        union = pred_cnt + lab_cnt - correct
        present = union > 0
        iou = jnp.where(present, correct / jnp.maximum(union, 1), 0.0)
        miou = iou.sum() / jnp.maximum(present.sum(), 1)
        wrong = lab_cnt - correct
        return miou.astype(jnp.float32), wrong, correct

    prim = primitive(name="mean_iou", nondiff=(0, 1))(fn)
    return prim(input, label)


def iou_similarity(x, y, box_normalized=True, name=None):
    """Pairwise IoU matrix [N, M] (reference: detection/iou_similarity_op)."""
    from ..core.dispatch import primitive, ensure_tensor
    import jax.numpy as jnp
    x, y = ensure_tensor(x), ensure_tensor(y)
    off = 0.0 if box_normalized else 1.0

    def fn(a, b):
        ax1, ay1, ax2, ay2 = a[:, 0], a[:, 1], a[:, 2], a[:, 3]
        bx1, by1, bx2, by2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
        area_a = (ax2 - ax1 + off) * (ay2 - ay1 + off)
        area_b = (bx2 - bx1 + off) * (by2 - by1 + off)
        ix1 = jnp.maximum(ax1[:, None], bx1[None])
        iy1 = jnp.maximum(ay1[:, None], by1[None])
        ix2 = jnp.minimum(ax2[:, None], bx2[None])
        iy2 = jnp.minimum(ay2[:, None], by2[None])
        iw = jnp.maximum(ix2 - ix1 + off, 0.0)
        ih = jnp.maximum(iy2 - iy1 + off, 0.0)
        inter = iw * ih
        return inter / jnp.maximum(
            area_a[:, None] + area_b[None] - inter, 1e-10)

    return primitive(name="iou_similarity")(fn)(x, y)


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    """Sample one category id per row from probability rows
    (reference sampling_id_op.cc)."""
    import jax
    from ..core import rng as _rng
    from ..core.dispatch import primitive, ensure_tensor
    x = ensure_tensor(x)
    key = (jax.random.key(seed) if seed else _rng.next_key())

    def fn(p):
        return jax.random.categorical(key, jnp_log(p), axis=-1)

    import jax.numpy as _jnp

    def jnp_log(p):
        return _jnp.log(_jnp.maximum(p, 1e-20))

    return primitive(name="sampling_id", nondiff=(0,))(fn)(x).astype(dtype)


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,
                       name=None):
    """Greedy CTC decode (reference ctc_align_op.cc): argmax per step,
    merge repeats, drop blanks.  Dense form: returns (decoded [B, T],
    out_lengths [B])."""
    from ..core.dispatch import primitive, ensure_tensor
    import jax.numpy as jnp
    input = ensure_tensor(input)
    t_extent = int(input.shape[1])
    args = [input]
    if input_length is not None:
        args.append(ensure_tensor(input_length))

    def fn(x, *ln):
        ids = jnp.argmax(x, axis=-1)  # [B, T]
        prev = jnp.concatenate(
            [jnp.full_like(ids[:, :1], -1), ids[:, :-1]], axis=1)
        keep = (ids != blank) & (ids != prev)
        if ln:
            valid = (jnp.arange(t_extent)[None, :]
                     < ln[0].reshape(-1, 1).astype(jnp.int32))
            keep = keep & valid
        # stable-compact kept ids to the front of each row
        pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
        dest = jnp.where(keep, pos, t_extent)
        out = jnp.full((ids.shape[0], t_extent + 1), padding_value,
                       ids.dtype)
        b = jnp.broadcast_to(
            jnp.arange(ids.shape[0], dtype=jnp.int32)[:, None], ids.shape)
        out = out.at[b, dest].set(jnp.where(keep, ids, padding_value))
        return out[:, :t_extent], keep.sum(axis=1)

    prim = primitive(name="ctc_greedy_decoder",
                     nondiff=tuple(_builtins.range(len(args))))(fn)
    return prim(*args)


def lod_reset(x, y=None, target_lod=None):
    """Dense+lengths form: re-interpret x with new lengths (reference
    lod_reset_op.cc).  Returns (x, lengths) — lengths from `y`'s second
    element / a lengths Tensor / the target_lod offsets list."""
    import numpy as _np
    from ..core.tensor import Tensor as _T
    if y is not None:
        lengths = y[1] if isinstance(y, (tuple, list)) else y
        return x, lengths
    if target_lod is not None:
        off = _np.asarray(target_lod, _np.int64)
        return x, _T(off[1:] - off[:-1])
    raise ValueError("lod_reset: provide y or target_lod")


def lod_append(x, level):
    """Append one LoD level at the bottom (reference:
    fluid/layers/nn.py lod_append over lod_reset_op with append=True).
    The round-4 nested RaggedTensor makes this expressible: the old
    bottom level becomes an outer level grouping the new one.

    ``x`` dense [N, ...]: returns a RaggedTensor whose rows are given
    by ``level`` (lengths, sum == N).  ``x`` RaggedTensor: ``level``
    must contain one entry per current bottom sequence-slot
    (len(level) == old bottom total) and its lengths re-split the value
    rows; the old row_splits are pushed onto ``outer_lods``.
    """
    import numpy as _np
    from ..core.ragged import RaggedTensor as _RT
    from ..core.dispatch import ensure_tensor as _ens
    from ..core.tensor import Tensor as _T

    lens = _np.asarray(
        level.numpy() if hasattr(level, "numpy") else level,
        _np.int64).reshape(-1)
    splits = _T(_np.concatenate([[0], _np.cumsum(lens)]).astype(
        _np.int32))
    if isinstance(x, _RT):
        total = int(_np.asarray(x.row_splits.numpy())[-1])
        if len(lens) != total:
            raise ValueError(
                f"lod_append: level has {len(lens)} entries but the "
                f"current bottom level spans {total} (reference "
                "enforces the level sizes match)")
        return _RT(x.values, splits,
                   outer_lods=x.outer_lods + (x.row_splits,))
    x = _ens(x)
    if int(_np.sum(lens)) != int(x.shape[0]):
        raise ValueError(
            f"lod_append: level sums to {int(_np.sum(lens))} but x has "
            f"{int(x.shape[0])} rows")
    return _RT(x, splits)


def inplace_abn(input, act=None, **kwargs):
    from ..nn import functional as _F
    out = _F.batch_norm(input, **{k: v for k, v in kwargs.items()
                                  if k in ("running_mean", "running_var",
                                           "weight", "bias", "training",
                                           "momentum", "epsilon")})
    if act:
        out = getattr(_F, act)(out)
    return out


def hsigmoid(input, label, num_classes, weight=None, bias=None,
             name=None, **kwargs):
    from ..nn import functional as _F
    if weight is None:
        raise ValueError(
            "hsigmoid: pass weight ([num_classes-1, D]) explicitly — "
            "param_attr creation belongs to nn.HSigmoidLoss here")
    return _F.hsigmoid_loss(input, label, num_classes, weight, bias)


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1,
                                       remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0, **kwargs):
    """Sampled softmax CE (reference: sample_logits_op.h:189 + the
    fluid.layers.sampled_softmax_with_cross_entropy:1026 composition).

    Host-side per-row sampling exactly like the reference CPU-only
    kernel ("this kernel only runs on cpu", sample_logits_op.h:194):
    unique log-uniform negatives per example (math/sampler.cc:42), the
    at-least-once probability adjustment (sample_prob.h:40
    ``adjust_prob``), logQ subtraction, and the 1e20 accidental-hit
    knockout (sample_logits_op.h:166).  The gather and the softmax CE
    run on device through the tape, so gradients reach ``logits`` at
    the sampled columns only (the reference's scatter-grad).

    Note: on TPU a FULL softmax_with_cross_entropy over the MXU is
    usually faster unless num_classes is extreme — this exists for
    training-recipe parity.
    """
    import numpy as _np
    import jax as _jax
    import jax.numpy as _jnp
    from ..core.dispatch import ensure_tensor, primitive
    from ..core.tensor import Tensor as _T

    logits = ensure_tensor(logits)
    N, K = int(logits.shape[0]), int(logits.shape[1])
    lab = _np.asarray(ensure_tensor(label).numpy(),
                      _np.int64).reshape(N, -1)
    T = int(num_true)
    S = int(num_samples)
    if lab.shape[1] != T:
        raise ValueError(
            f"sampled_softmax_with_cross_entropy: label has "
            f"{lab.shape[1]} true classes per row, num_true={T}")

    if use_customized_samples:
        samples = _np.asarray(ensure_tensor(customized_samples).numpy(),
                              _np.int64)
        q = _np.asarray(ensure_tensor(customized_probabilities).numpy(),
                        _np.float32)
    else:
        max_true = max(len(set(lab[i].tolist()))
                       for i in _np.arange(N)) if N else 0
        if S > K - max_true:
            raise ValueError(
                f"sampled_softmax_with_cross_entropy: num_samples={S} "
                f"unique negatives cannot be drawn from {K} classes "
                f"when a row has {max_true} distinct true label(s) — "
                "the rejection sampler would never terminate; use the "
                "full softmax_with_cross_entropy instead")
        rng = _np.random if seed == 0 else _np.random.RandomState(seed)
        log_range = _np.log(K + 1)
        samples = _np.empty((N, T + S), _np.int64)
        q = _np.empty((N, T + S), _np.float32)

        def p_log_uniform(v):
            return _np.log((v + 2.0) / (v + 1.0)) / log_range

        for i in _np.arange(N):  # builtins.range is shadowed by the op
            samples[i, :T] = lab[i]
            seen = set(lab[i].tolist())
            j, tries = 0, 0
            while j < S:
                tries += 1
                v = int(_np.exp(rng.random_sample() * log_range)) - 1
                v %= K
                if v not in seen:
                    seen.add(v)
                    samples[i, T + j] = v
                    j += 1
            p = p_log_uniform(samples[i].astype(_np.float64))
            # adjust_prob: P(appears in `tries` draws) for unique
            # sampling; identity*S when every draw was accepted
            q[i] = (p * S if tries == S
                    else -_np.expm1(tries * _np.log1p(-p)))

    # accidental hits: a NEGATIVE column that equals one of the row's
    # true labels is knocked out before the softmax
    knock = _np.zeros((N, T + S), _np.float32)
    if remove_accidental_hits:
        hit = (samples[:, T:, None] == samples[:, None, :T]).any(-1)
        knock[:, T:] = _np.where(hit, -1e20, 0.0).astype(_np.float32)

    log_q = _np.clip(_np.log(_np.maximum(q, 1e-30)), -1e20,
                     1e20).astype(_np.float32)
    samples_j = _jnp.asarray(samples)
    adj = _jnp.asarray(knock - log_q)

    def fn(lg):
        sampled = _jnp.take_along_axis(lg, samples_j, axis=1) + adj
        logp = _jax.nn.log_softmax(sampled, axis=-1)
        return -logp[:, :T].mean(axis=-1, keepdims=True)

    return primitive(name="sampled_softmax_with_cross_entropy")(fn)(logits)


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False, gaussian_sigma=2.0,
               background_label=0, normalized=True, return_index=False,
               return_rois_num=True, name=None):
    """Matrix NMS (reference: detection/matrix_nms_op.cc) — parallel
    soft-suppression by pairwise IoU decay.  Eager numpy."""
    import numpy as _np
    from ..core.dispatch import ensure_tensor
    bb = _np.asarray(ensure_tensor(bboxes).numpy(), _np.float32)
    sc = _np.asarray(ensure_tensor(scores).numpy(), _np.float32)
    outs, idxs, counts = [], [], []
    off = 0.0 if normalized else 1.0
    for b in _builtins.range(bb.shape[0]):
        dets = []
        for c in _builtins.range(sc.shape[1]):
            if c == background_label:
                continue
            s = sc[b, c]
            keep = _np.where(s > score_threshold)[0]
            if not len(keep):
                continue
            order = keep[_np.argsort(-s[keep])][:nms_top_k]
            boxes, ss = bb[b][order], s[order]
            x1, y1, x2, y2 = boxes.T
            area = (x2 - x1 + off) * (y2 - y1 + off)
            ix1 = _np.maximum(x1[:, None], x1[None])
            iy1 = _np.maximum(y1[:, None], y1[None])
            ix2 = _np.minimum(x2[:, None], x2[None])
            iy2 = _np.minimum(y2[:, None], y2[None])
            iw = _np.maximum(ix2 - ix1 + off, 0)
            ih = _np.maximum(iy2 - iy1 + off, 0)
            iou = iw * ih / _np.maximum(
                area[:, None] + area[None] - iw * ih, 1e-10)
            iou = _np.triu(iou, k=1)
            iou_cmax = iou.max(axis=0)
            # decay_j = min_i f(iou_ij, compensate_i): the compensation is
            # the SUPPRESSOR's own max-IoU (matrix_nms_op.cc), row axis
            if use_gaussian:
                decay = _np.exp(-(iou ** 2 - iou_cmax[:, None] ** 2)
                                / gaussian_sigma).min(axis=0)
            else:
                decay = ((1 - iou) / _np.maximum(
                    1 - iou_cmax[:, None], 1e-10)).min(axis=0)
            ds = ss * decay
            sel = ds > post_threshold
            for i in _np.where(sel)[0]:
                dets.append([c, ds[i], *boxes[i], order[i]])
        dets.sort(key=lambda d: -d[1])
        dets = dets[:keep_top_k] if keep_top_k > 0 else dets
        outs.append(_np.asarray([d[:6] for d in dets], _np.float32)
                    if dets else _np.zeros((0, 6), _np.float32))
        idxs.append(_np.asarray([d[6] for d in dets], _np.int32))
        counts.append(len(dets))
    from ..core.tensor import Tensor as _T
    out = _T(_np.concatenate(outs, axis=0))
    res = [out]
    if return_index:
        res.append(_T(_np.concatenate(idxs, axis=0)[:, None]))
    if return_rois_num:
        res.append(_T(_np.asarray(counts, _np.int32)))
    return tuple(res) if len(res) > 1 else out


import numpy as _np  # noqa: E402  (host-side NMS helpers below)


def _poly_area(p):
    x, y = p[:, 0], p[:, 1]
    return 0.5 * abs(float(_np.dot(x, _np.roll(y, -1))
                           - _np.dot(y, _np.roll(x, -1))))


def _poly_clip(subject, clip):
    """Sutherland–Hodgman convex clipping (host-side)."""
    out = list(subject)
    for i in _builtins.range(len(clip)):
        a, b = clip[i], clip[(i + 1) % len(clip)]
        if not out:
            return _np.zeros((0, 2), _np.float64)
        inp, out = out, []

        def inside(p):
            return ((b[0] - a[0]) * (p[1] - a[1])
                    - (b[1] - a[1]) * (p[0] - a[0])) >= 0

        def intersect(p, q):
            d1 = (b[0] - a[0]) * (p[1] - a[1]) \
                - (b[1] - a[1]) * (p[0] - a[0])
            d2 = (b[0] - a[0]) * (q[1] - a[1]) \
                - (b[1] - a[1]) * (q[0] - a[0])
            t = d1 / (d1 - d2) if d1 != d2 else 0.0
            return p + t * (q - p)

        for j in _builtins.range(len(inp)):
            p, q = inp[j], inp[(j + 1) % len(inp)]
            if inside(q):
                if not inside(p):
                    out.append(intersect(p, q))
                out.append(q)
            elif inside(p):
                out.append(intersect(p, q))
    return _np.asarray(out, _np.float64)


def _pair_iou(b1, b2, normalized):
    """IoU for 4-coord corner boxes or 2k-coord polygons (convex
    clipping; EAST quads are convex in practice)."""
    if b1.shape[-1] == 4:
        off = 0.0 if normalized else 1.0
        ix1, iy1 = max(b1[0], b2[0]), max(b1[1], b2[1])
        ix2, iy2 = min(b1[2], b2[2]), min(b1[3], b2[3])
        iw, ih = max(ix2 - ix1 + off, 0), max(iy2 - iy1 + off, 0)
        inter = iw * ih
        a1 = (b1[2] - b1[0] + off) * (b1[3] - b1[1] + off)
        a2 = (b2[2] - b2[0] + off) * (b2[3] - b2[1] + off)
        return inter / max(a1 + a2 - inter, 1e-10)
    p1 = b1.reshape(-1, 2).astype(_np.float64)
    p2 = b2.reshape(-1, 2).astype(_np.float64)

    def _signed_area(p):  # shoelace WITHOUT abs: sign = orientation
        x, y = p[:, 0], p[:, 1]
        return 0.5 * float(_np.dot(x, _np.roll(y, -1))
                           - _np.dot(y, _np.roll(x, -1)))

    # orient counter-clockwise for the clipper (signed area is robust
    # to collinear leading vertices, unlike a single corner cross)
    if _signed_area(p1) < 0:
        p1 = p1[::-1]
    if _signed_area(p2) < 0:
        p2 = p2[::-1]
    inter_poly = _poly_clip(p1, p2)
    inter = _poly_area(inter_poly) if len(inter_poly) >= 3 else 0.0
    union = _poly_area(p1) + _poly_area(p2) - inter
    return inter / max(union, 1e-10)


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    """EAST-style locality-aware NMS (reference:
    detection/locality_aware_nms_op.cc, CPU-only there too).

    Single image: ``bboxes`` [M, B] with B = 4 (corner boxes) or an
    even 2k >= 8 (polygons, merged via convex clipping IoU);
    ``scores`` [C, M].  Pass 1 walks boxes in INPUT order,
    score-weighted-merging each box into the running accumulator while
    overlap > nms_threshold (scores add up) — the locality pass that
    fuses EAST's dense per-pixel quads.  Pass 2 is standard NMS with
    the adaptive-eta threshold over the merged boxes.  Returns
    (out [keep_top_k, 2 + B] rows = [label, score, coords...] padded
    with -1, valid_count).
    """
    from ..core.dispatch import ensure_tensor
    from ..core.tensor import Tensor as _T
    b = _np.asarray(ensure_tensor(bboxes).numpy(), _np.float64)
    s = _np.asarray(ensure_tensor(scores).numpy(), _np.float64)
    C, M = s.shape
    B = b.shape[-1]
    if B != 4 and (B < 8 or B % 2):
        raise ValueError(
            f"locality_aware_nms: box width must be 4 or an even "
            f"number >= 8, got {B}")
    rows = []
    for cls in _builtins.range(C):
        if cls == background_label:
            continue
        boxes_c = b.copy()
        sc = s[cls].copy()
        # pass 1: locality-aware weighted merge, input order
        skip = _np.ones(M, bool)
        idx = -1
        for i in _builtins.range(M):
            if idx > -1:
                ov = _pair_iou(boxes_c[i], boxes_c[idx], normalized)
                if ov > nms_threshold:
                    boxes_c[idx] = (boxes_c[i] * sc[i]
                                    + boxes_c[idx] * sc[idx]) \
                        / (sc[i] + sc[idx])
                    sc[idx] += sc[i]
                else:
                    skip[idx] = False
                    idx = i
            else:
                idx = i
        if idx > -1:
            skip[idx] = False
        cand = [i for i in _builtins.range(M)
                if sc[i] > score_threshold and not skip[i]]
        cand.sort(key=lambda i: -sc[i])
        if nms_top_k > -1:
            cand = cand[:nms_top_k]
        # pass 2: standard NMS with adaptive eta
        kept = []
        thr = float(nms_threshold)
        for i in cand:
            ok = all(_pair_iou(boxes_c[i], boxes_c[j],
                               normalized) <= thr for j in kept)
            if ok:
                kept.append(i)
                if nms_eta < 1.0 and thr > 0.5:
                    thr *= nms_eta
        for i in kept:
            rows.append([float(cls), float(sc[i])]
                        + boxes_c[i].tolist())
    rows.sort(key=lambda r: -r[1])
    if keep_top_k > -1:  # -1 = keep all (Paddle sentinel)
        rows = rows[:keep_top_k]
    count = len(rows)
    pad_to = keep_top_k if keep_top_k > -1 else max(count, 1)
    out = _np.full((pad_to, 2 + B), -1.0, _np.float32)
    if rows:
        out[:count] = _np.asarray(rows, _np.float32)
    return _T(out), _T(_np.int32(count))


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, **kwargs):
    """Reference fluid/layers/detection.py ssd_loss — real composition
    (matching + hard negative mining + smooth-L1/CE), see
    nn/functional/legacy.py:ssd_loss."""
    from ..nn.functional.legacy import ssd_loss as _impl
    return _impl(location, confidence, gt_box, gt_label, prior_box,
                 prior_box_var=prior_box_var, **kwargs)


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """Chunk precision/recall/F1 for one batch of tag rows (reference:
    chunk_eval_op.cc).  Eager host computation; returns the op's 6
    outputs (precision, recall, f1, num_infer, num_label, num_correct)."""
    import numpy as _np
    from ..core.dispatch import ensure_tensor
    from ..core.tensor import Tensor as _T
    from .metrics import chunk_count
    inf = _np.asarray(ensure_tensor(input).numpy())
    lab = _np.asarray(ensure_tensor(label).numpy())
    lens = (_np.asarray(ensure_tensor(seq_length).numpy()).reshape(-1)
            if seq_length is not None else None)
    ni, nl, nc = chunk_count(inf, lab, chunk_scheme, num_chunk_types,
                             excluded_chunk_types, lens)
    precision = nc / ni if ni else 0.0
    recall = nc / nl if nl else 0.0
    f1 = 2 * precision * recall / (precision + recall) if nc else 0.0
    mk = lambda v, dt: _T(_np.asarray([v], dt))  # noqa: E731
    return (mk(precision, _np.float32), mk(recall, _np.float32),
            mk(f1, _np.float32), mk(ni, _np.int64), mk(nl, _np.int64),
            mk(nc, _np.int64))
