"""``fluid.layers`` — the 1.x layer/op namespace.

Reference parity: ``python/paddle/fluid/layers/`` (nn.py, tensor.py,
control_flow.py, detection.py…), the surface 1.x model code builds on.
Everything maps to the modern ops; graph building works because the ops
record into the default Program under ``paddle.enable_static()``.
"""
from __future__ import annotations

# graph-building layers (create parameters)
from ..static.nn import (  # noqa: F401
    fc, conv2d, batch_norm, embedding, dropout,
    cond, while_loop, case, switch_case)

# tensor ops under their fluid names
from ..ops.compat_ops import (  # noqa: F401
    fill_constant, create_global_var, create_parameter, elementwise_add,
    elementwise_sub, elementwise_mul, elementwise_div, elementwise_pow,
    elementwise_mod, elementwise_floordiv, elementwise_max,
    elementwise_min, reduce_sum, reduce_mean, reduce_max, reduce_min,
    reduce_prod, has_inf, has_nan, shape, slice, strided_slice,
    crop_tensor, unstack, create_array, array_write, array_read,
    array_length)
from ..ops.math import (  # noqa: F401
    abs, exp, log, sqrt, square, sin, cos, tanh, sigmoid, clip, scale,
    cumsum, pow, matmul)
from ..ops.creation import (  # noqa: F401
    zeros, ones, full, arange, linspace, assign)
from ..ops.manipulation import (  # noqa: F401
    concat, split, reshape, transpose, squeeze, unsqueeze, stack,
    gather, gather_nd, scatter, expand_as, cast, one_hot, topk, argsort,
    where)
from ..nn.functional import (  # noqa: F401
    relu, softmax, cross_entropy, log_softmax, pad, pool2d,
    image_resize, grid_sample, bilinear_tensor_product, dice_loss,
    linear_chain_crf)
from ..nn.functional.loss import (  # noqa: F401
    square_error_cost, softmax_with_cross_entropy)
from ..static.compat import accuracy, auc, Print  # noqa: F401
from ..vision.ops import (  # noqa: F401
    yolo_box, prior_box, box_coder, multiclass_nms, roi_align, roi_pool)

# sequence layers
from ..nn.functional.sequence import (  # noqa: F401
    sequence_pad, sequence_unpad, sequence_pool, sequence_softmax,
    sequence_expand, sequence_reverse)


def mean(x, name=None):
    from ..ops.math import mean as _mean
    return _mean(x)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    """reference mul_op.cc: flatten x after x_num_col_dims and y after
    y_num_col_dims, 2-D matmul, restore the leading dims."""
    import numpy as _np
    from ..ops.math import matmul as _matmul
    from ..ops.manipulation import reshape as _reshape
    x_lead = list(x.shape[:x_num_col_dims])
    x_flat = _reshape(x, [int(_np.prod(x_lead) or 1), -1])
    y_tail = list(y.shape[y_num_col_dims:])
    y_flat = _reshape(y, [-1, int(_np.prod(y_tail) or 1)])
    out = _matmul(x_flat, y_flat)
    return _reshape(out, x_lead + y_tail)


def data(name, shape, dtype="float32", lod_level=0,
         append_batch_size=True):
    """fluid.layers.data prepends the batch dim when append_batch_size
    (1.x convention); dynamic dims are rejected on TPU — declare the
    batch size explicitly."""
    from ..static.program import data as _data
    if append_batch_size:
        raise ValueError(
            "fluid.layers.data(append_batch_size=True) implies a dynamic "
            "batch dim, unsupported on the TPU backend; pass the full "
            "shape and append_batch_size=False")
    return _data(name, shape, dtype)
