"""``paddle.fluid`` compatibility namespace.

Reference parity: 1.x/2.0-era user code imports ``paddle.fluid as fluid``
pervasively (``python/paddle/fluid/__init__.py``).  This module re-exports
the modern equivalents under the fluid names so that era's scripts run:
``fluid.layers`` → static.nn + functional ops, ``fluid.dygraph`` → eager
mode helpers, ``fluid.Executor``/``fluid.data``/places → paddle.static.
"""
from __future__ import annotations

from ..static import (  # noqa: F401
    Program, Executor, program_guard, default_main_program,
    default_startup_program, global_scope, scope_guard, data,
    CompiledProgram, BuildStrategy, ExecutionStrategy, ParallelExecutor,
    device_guard)
from ..core.tensor import Tensor, Parameter  # noqa: F401
from ..nn.param_attr import ParamAttr  # noqa: F401
from ..core.device import (  # noqa: F401
    set_device, get_device, is_compiled_with_cuda)
from .. import (  # noqa: F401
    CPUPlace, CUDAPlace, CUDAPinnedPlace, XPUPlace,
    LoDTensor, LoDTensorArray)
from ..framework.io import save, load  # noqa: F401
from .. import optimizer  # noqa: F401
from .. import io  # noqa: F401
from .. import regularizer  # noqa: F401
from ..nn import initializer  # noqa: F401
from ..nn import clip  # noqa: F401
from ..io.native_dataset import DatasetFactory  # noqa: F401
from . import layers  # noqa: F401
from . import dygraph  # noqa: F401


def enable_dygraph(place=None):
    from ..static.program import disable_static
    disable_static()


def disable_dygraph():
    from ..static.program import enable_static
    enable_static()


def in_dygraph_mode():
    from ..static.program import in_dynamic_mode
    return in_dynamic_mode()


def cuda_places(device_ids=None):
    from ..static.compat import cuda_places as _cp
    return _cp(device_ids)


def cpu_places(device_count=None):
    from ..static.compat import cpu_places as _cp
    return _cp(device_count)
