"""``paddle.fluid`` compatibility namespace.

Reference parity: 1.x/2.0-era user code imports ``paddle.fluid as fluid``
pervasively (``python/paddle/fluid/__init__.py``).  This module re-exports
the modern equivalents under the fluid names so that era's scripts run:
``fluid.layers`` → static.nn + functional ops, ``fluid.dygraph`` → eager
mode helpers, ``fluid.Executor``/``fluid.data``/places → paddle.static.
"""
from __future__ import annotations

from ..static import (  # noqa: F401
    Program, Executor, program_guard, default_main_program,
    default_startup_program, global_scope, scope_guard, data,
    CompiledProgram, BuildStrategy, ExecutionStrategy, ParallelExecutor,
    device_guard)
from ..core.tensor import Tensor, Parameter  # noqa: F401
from ..nn.param_attr import ParamAttr  # noqa: F401
from ..core.device import (  # noqa: F401
    set_device, get_device, is_compiled_with_cuda)
from .. import (  # noqa: F401
    CPUPlace, CUDAPlace, CUDAPinnedPlace, XPUPlace,
    LoDTensor, LoDTensorArray)
from ..framework.io import save, load  # noqa: F401
from . import optimizer  # noqa: F401  (1.x *Optimizer names + EMA etc.)
from . import io  # noqa: F401  (1.x save/load_params surface)
from .. import regularizer  # noqa: F401
from ..nn import initializer  # noqa: F401
from ..nn import clip  # noqa: F401
from ..io.native_dataset import DatasetFactory  # noqa: F401
from . import layers  # noqa: F401
from . import dygraph  # noqa: F401


def enable_dygraph(place=None):
    from ..static.program import disable_static
    disable_static()


def disable_dygraph():
    from ..static.program import enable_static
    enable_static()


def in_dygraph_mode():
    from ..static.program import in_dynamic_mode
    return in_dynamic_mode()


def cuda_places(device_ids=None):
    from ..static.compat import cuda_places as _cp
    return _cp(device_ids)


def cpu_places(device_count=None):
    from ..static.compat import cpu_places as _cp
    return _cp(device_count)

# -- remaining 1.x submodules ---------------------------------------------
from . import nets  # noqa: E402,F401
from . import contrib  # noqa: E402,F401  (slim.quantization QAT)
from ..utils import unique_name  # noqa: E402,F401
from .. import incubate  # noqa: E402,F401
from . import metrics  # noqa: E402,F401
from ..utils import profiler  # noqa: E402,F401
from ..io import native_dataset as dataset  # noqa: E402,F401
from ..core import rng as generator  # noqa: E402,F401

import sys as _sys
import types as _types


def _submodule(name, **attrs):
    m = _types.ModuleType(f"{__name__}.{name}")
    for k, v in attrs.items():
        setattr(m, k, v)
    _sys.modules[m.__name__] = m
    globals()[name] = m
    return m


# fluid.backward (append_backward/gradients over the deferred graph)
from ..static.program import append_backward as _ab  # noqa: E402
from ..static import gradients as _grads  # noqa: E402
backward = _submodule("backward", append_backward=_ab, gradients=_grads)

# fluid.executor / fluid.framework / fluid.compiler mirror the reference
# module split (executor.py / framework.py / compiler.py)
from ..static import (  # noqa: E402
    Program as _Prog, Executor as _Exe, global_scope as _gs,
    scope_guard as _sg, program_guard as _pg,
    default_main_program as _dmp, default_startup_program as _dsp,
    CompiledProgram as _CP, BuildStrategy as _BS,
    ExecutionStrategy as _ES, ParallelExecutor as _PE)
executor = _submodule("executor", Executor=_Exe, global_scope=_gs,
                      scope_guard=_sg)
framework = _submodule(
    "framework", Program=_Prog, program_guard=_pg,
    default_main_program=_dmp, default_startup_program=_dsp,
    in_dygraph_mode=in_dygraph_mode, Parameter=Parameter)
compiler = _submodule("compiler", CompiledProgram=_CP, BuildStrategy=_BS,
                      ExecutionStrategy=_ES)
parallel_executor = _submodule("parallel_executor", ParallelExecutor=_PE)


# fluid.average (WeightedAverage)
class WeightedAverage:
    """reference: fluid/average.py — streaming weighted mean."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._total = 0.0
        self._weight = 0.0

    def add(self, value, weight=1):
        import numpy as _np
        self._total += float(_np.asarray(value).sum()) * float(weight)
        self._weight += float(weight)

    def eval(self):
        if self._weight <= 0:
            raise ValueError(
                "WeightedAverage.eval(): no values added yet "
                "(reference fluid/average.py enforce)")
        return self._total / self._weight


average = _submodule("average", WeightedAverage=WeightedAverage)


class _DeprecatedLookupError(AttributeError, NotImplementedError):
    """AttributeError so hasattr/dir feature-probing stays protocol-
    correct; NotImplementedError so direct use reads as a scope note."""


def _deprecated_module(name, why):
    m = _submodule(name)

    def _getattr(attr, _why=why, _name=name):
        raise _DeprecatedLookupError(f"fluid.{_name}.{attr}: {_why}")
    m.__getattr__ = _getattr
    return m


# deprecated-in-reference or PS-era descriptors: kept as named modules with
# actionable errors
_deprecated_module(
    "evaluator", "fluid.evaluator was deprecated in the reference; use "
    "fluid.metrics (ChunkEvaluator/EditDistance/DetectionMAP) or "
    "paddle.metric")
_deprecated_module(
    "data_feed_desc", "dataset descriptors are internal to the native "
    "dataset engine (io/native_dataset.py)")
_deprecated_module(
    "trainer_desc", "trainer descriptors are internal to "
    "Executor.train_from_dataset")
_deprecated_module(
    "distribute_lookup_table", "distributed lookup tables live in "
    "paddle.distributed.ps (SparseTable)")


# fluid.transpiler: the legacy PS program rewriter — map the entry points
# onto the modern fleet/ps machinery
class DistributeTranspilerConfig:
    def __init__(self):
        self.slice_var_up = True
        self.split_method = None
        self.min_block_size = 8192
        self.sync_mode = True


class DistributeTranspiler:
    """reference: fluid/transpiler/distribute_transpiler.py — rewrote a
    program into trainer/pserver pairs wired over grpc.

    Round-5 sync-mode shim: under SPMD there are no server processes to
    split a program FOR — parameters are mesh-resident and gradient
    sync is XLA collectives — so the 1.x entry points map to:

    * ``transpile``            → record the topology env contract
      (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM, like the reference's
      env plumbing in ``launch_utils.py``) and keep the program whole;
    * ``get_trainer_program``  → the ORIGINAL program: every trainer
      runs the full graph, dp sync is the executor's job;
    * ``get_pserver_program``  → an EMPTY runnable program (there is no
      listen_and_serv loop; the "server" role returns immediately) plus
      a matching startup program via ``get_startup_program`` (or both
      at once via ``get_pserver_programs``).

    A 1.x PS script therefore runs unmodified in sync mode
    (``tests/test_transpiler_shim.py``).  Async (sync_mode=False) keeps
    the guided raise — its semantics live in the geo tables
    (``paddle.distributed.ps.GeoSparseTable``)."""

    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self.trainer_id = 0
        self.trainers = 1
        self._main = None

    def transpile(self, trainer_id, program=None, pservers="",
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint=""):
        if not (sync_mode and self.config.sync_mode):
            raise NotImplementedError(
                "DistributeTranspiler(sync_mode=False): the async grpc "
                "PS rewrite has no SPMD analogue — use "
                "paddle.distributed.ps.GeoSparseTable/GeoWorkerTable "
                "for geo-async semantics, or fleet DistributedStrategy "
                "a_sync")
        import os as _os
        from .. import static as _static
        self.trainer_id = int(trainer_id)
        self.trainers = int(trainers) if not isinstance(trainers, str) \
            else len([e for e in trainers.split(",") if e])
        self.pserver_endpoints = [e for e in str(pservers).split(",")
                                  if e]
        self._main = program or _static.default_main_program()
        self._startup = startup_program or \
            _static.default_startup_program()
        _os.environ["PADDLE_TRAINER_ID"] = str(self.trainer_id)
        _os.environ["PADDLE_TRAINERS_NUM"] = str(self.trainers)
        return self._main

    def get_trainer_program(self, wait_port=True):
        if self._main is None:
            raise RuntimeError(
                "DistributeTranspiler.get_trainer_program: call "
                "transpile() first (reference enforces the same order)")
        return self._main

    def get_pserver_program(self, endpoint):
        from ..static import Program
        return Program()   # no server loop under SPMD; returns empty

    def get_pserver_programs(self, endpoint):
        return self.get_pserver_program(endpoint), \
            self.get_startup_program(endpoint)

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        from ..static import Program
        return Program()


transpiler = _submodule(
    "transpiler", DistributeTranspiler=DistributeTranspiler,
    DistributeTranspilerConfig=DistributeTranspilerConfig,
    HashName=None, RoundRobin=None)
DistributeTranspiler_ = DistributeTranspiler


install_check = _submodule("install_check")


def _install_run_check():
    from ..utils import run_check as _rc
    return _rc()


install_check.run_check = _install_run_check

# fluid.contrib.mixed_precision: the decorator path 1.x AMP scripts use
# — attached onto the REAL contrib package (imported above; a synthetic
# stub here would shadow contrib.layers / contrib.slim)
from ..static import amp as _static_amp  # noqa: E402
contrib.mixed_precision = _static_amp
_sys.modules[f"{__name__}.contrib.mixed_precision"] = _static_amp


from ..io import DataFeeder  # noqa: E402,F401  (shared legacy feeder)
