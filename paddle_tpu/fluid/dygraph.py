"""``fluid.dygraph`` — 1.x eager-mode namespace.

Reference parity: ``python/paddle/fluid/dygraph/`` (guard, to_variable,
Layer, layer containers, jit helpers).
"""
from __future__ import annotations

import contextlib

from ..nn.layer.base import Layer, LayerList, Sequential  # noqa: F401
from ..nn import ParamAttr  # noqa: F401
from ..core.tensor import Tensor, to_tensor  # noqa: F401
from ..distributed.parallel import DataParallel, ParallelEnv  # noqa: F401
from ..jit import to_static as declarative  # noqa: F401
from ..jit import ProgramTranslator, TracedLayer  # noqa: F401
from ..optimizer.lr import LearningRateDecay  # noqa: F401


@contextlib.contextmanager
def guard(place=None):
    """1.x dygraph guard: eager mode within the block."""
    from ..static.program import (in_static_mode, enable_static,
                                  disable_static)
    was_static = in_static_mode()
    disable_static()
    try:
        yield
    finally:
        if was_static:
            enable_static()


def to_variable(value, name=None, zero_copy=None, dtype=None):
    return to_tensor(value, dtype=dtype)


def enabled():
    from ..static.program import in_dynamic_mode
    return in_dynamic_mode()


# 1.x layer-class aliases
from ..nn import (  # noqa: F401,E402
    Linear, Embedding, Conv2D, BatchNorm, LayerNorm, Dropout,
)


class Pool2D(Layer):
    """1.x Pool2D layer (reference: fluid/dygraph/nn.py Pool2D)."""

    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, ceil_mode=False,
                 exclusive=True, data_format="NCHW"):
        super().__init__()
        self._args = (pool_size, pool_type, pool_stride, pool_padding,
                      global_pooling, ceil_mode, exclusive, data_format)

    def forward(self, x):
        from ..nn.functional import pool2d
        (size, ptype, stride, pad, gp, ceil, excl, fmt) = self._args
        return pool2d(x, pool_size=size, pool_type=ptype,
                      pool_stride=stride, pool_padding=pad,
                      global_pooling=gp, ceil_mode=ceil,
                      exclusive=excl, data_format=fmt)
