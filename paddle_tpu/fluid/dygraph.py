"""``fluid.dygraph`` — 1.x eager-mode namespace.

Reference parity: ``python/paddle/fluid/dygraph/`` (guard, to_variable,
Layer, layer containers, jit helpers).
"""
from __future__ import annotations

import contextlib

from ..nn.layer.base import Layer, LayerList, Sequential  # noqa: F401
from ..nn import ParamAttr  # noqa: F401
from ..core.tensor import Tensor, to_tensor  # noqa: F401
from ..distributed.parallel import DataParallel, ParallelEnv  # noqa: F401
from ..jit import to_static as declarative  # noqa: F401
from ..jit import ProgramTranslator, TracedLayer  # noqa: F401
from ..optimizer.lr import LearningRateDecay  # noqa: F401


@contextlib.contextmanager
def guard(place=None):
    """1.x dygraph guard: eager mode within the block."""
    from ..static.program import (in_static_mode, enable_static,
                                  disable_static)
    was_static = in_static_mode()
    disable_static()
    try:
        yield
    finally:
        if was_static:
            enable_static()


def to_variable(value, name=None, zero_copy=None, dtype=None):
    return to_tensor(value, dtype=dtype)


def enabled():
    from ..static.program import in_dynamic_mode
    return in_dynamic_mode()


# 1.x layer-class aliases
from ..nn import (  # noqa: F401,E402
    Linear, Embedding, Conv2D, BatchNorm, LayerNorm, Dropout,
)


class Pool2D(Layer):
    """1.x Pool2D layer (reference: fluid/dygraph/nn.py Pool2D)."""

    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, ceil_mode=False,
                 exclusive=True, data_format="NCHW"):
        super().__init__()
        self._args = (pool_size, pool_type, pool_stride, pool_padding,
                      global_pooling, ceil_mode, exclusive, data_format)

    def forward(self, x):
        from ..nn.functional import pool2d
        (size, ptype, stride, pad, gp, ceil, excl, fmt) = self._args
        return pool2d(x, pool_size=size, pool_type=ptype,
                      pool_stride=stride, pool_padding=pad,
                      global_pooling=gp, ceil_mode=ceil,
                      exclusive=excl, data_format=fmt)


# -- base mode switches (reference: fluid/dygraph/base.py) ----------------
def enable_dygraph(place=None):
    from ..static.program import disable_static
    disable_static()


def disable_dygraph():
    from ..static.program import enable_static
    enable_static()


def grad(*args, **kwargs):
    import paddle_tpu as _p
    return _p.grad(*args, **kwargs)


def no_grad(fn=None):
    from ..core import autograd
    if fn is None:
        return autograd.no_grad()
    return autograd.no_grad()(fn)


no_grad_ = no_grad


# -- 1.x dygraph nn layer names (reference: fluid/dygraph/nn.py) ----------
from ..nn import (  # noqa: F401,E402
    Conv2DTranspose, Conv3D, Conv3DTranspose,
    Flatten, GroupNorm, SpectralNorm, ParameterList, Sequential as _Seq)
from ..nn import Bilinear as BilinearTensorProduct  # noqa: F401,E402
from ..nn import PReLU as PRelu  # noqa: F401,E402
from ..nn import InstanceNorm2D as InstanceNorm  # noqa: F401,E402
from ..nn import NCELoss as NCE  # noqa: F401,E402


class GRUUnit(Layer):
    """1.x GRUUnit layer (reference: fluid/dygraph/nn.py GRUUnit over
    gru_unit_op) — single GRU step on pre-projected gate input."""

    def __init__(self, size, param_attr=None, bias_attr=None,
                 activation="tanh", gate_activation="sigmoid",
                 origin_mode=False, dtype="float32"):
        super().__init__()
        d = size // 3
        self._size = d
        self._activation = activation
        self._gate_activation = gate_activation
        self._origin_mode = origin_mode
        from ..nn import initializer as I
        self.weight = self.create_parameter(
            [d, 3 * d], attr=param_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter([3 * d], attr=bias_attr,
                                          is_bias=True)

    def forward(self, input, hidden):
        from ..nn.functional import gru_unit
        return gru_unit(input, hidden, self.weight, self.bias,
                        activation=self._activation,
                        gate_activation=self._gate_activation,
                        origin_mode=self._origin_mode)


class TreeConv(Layer):
    """Tree-based convolution (reference: fluid/dygraph/nn.py TreeConv
    over tree_conv_op.cc): continuous binary-tree patch conv.  Nodes
    [B, N, D] with adjacency edges [B, E, 2]; each node aggregates its
    children through 3 positional weight matrices."""

    def __init__(self, feature_size, output_size, num_filters=1,
                 max_depth=2, act="tanh", param_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        from ..nn import initializer as I
        self.max_depth = max_depth
        self.act = act
        # 3 positional roles (self / left-weighted / right-weighted)
        self.weight = self.create_parameter(
            [3, feature_size, num_filters * output_size], attr=param_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(
            [num_filters * output_size], attr=bias_attr, is_bias=True)
        self._out = (num_filters, output_size)

    def forward(self, nodes_vector, edge_set):
        import jax.numpy as jnp
        from ..core.dispatch import primitive, ensure_tensor
        nodes = ensure_tensor(nodes_vector)
        edges = ensure_tensor(edge_set)
        nf, out = self._out

        @primitive(name="tree_conv", nondiff=(1,))
        def fn(x, e, w, b):
            bsz, n, d = x.shape
            e = e.astype(jnp.int32)
            parent, child = e[..., 0], e[..., 1]
            deg = jnp.zeros((bsz, n), x.dtype)
            bidx = jnp.broadcast_to(
                jnp.arange(bsz)[:, None], parent.shape)
            deg = deg.at[bidx, parent].add(1.0)
            # aggregate children features per parent
            agg = jnp.zeros_like(x)
            agg = agg.at[bidx, parent].add(
                jnp.take_along_axis(x, child[..., None], axis=1))
            self_t = x @ w[0]
            left_t = agg @ w[1]
            right_t = (agg / jnp.maximum(deg, 1.0)[..., None]) @ w[2]
            y = self_t + left_t + right_t + b
            return y.reshape(bsz, n, nf, out).max(axis=2)

        y = fn(nodes, edges, self.weight, self.bias)
        if self.act:
            from ..nn import functional as F
            y = getattr(F, self.act)(y)
        return y


# -- 1.x LR scheduler names (reference: dygraph/learning_rate_scheduler.py)
from ..optimizer.lr import (  # noqa: F401,E402
    ExponentialDecay, InverseTimeDecay, LambdaDecay, MultiStepDecay,
    NaturalExpDecay, NoamDecay, PiecewiseDecay, PolynomialDecay,
    StepDecay)
from ..optimizer.lr import CosineAnnealingDecay as CosineDecay  # noqa: F401,E402
from ..optimizer.lr import LinearWarmup as LinearLrWarmup  # noqa: F401,E402
from ..optimizer.lr import ReduceOnPlateau as ReduceLROnPlateau  # noqa: F401,E402


class StaticModelRunner:
    """reference: fluid/dygraph/static_runner.py — runs a saved inference
    program inside dygraph; jit.load returns the modern equivalent."""

    def __new__(cls, model_dir, model_filename=None, params_filename=None):
        from .. import jit as _jit
        import os as _os
        base = model_dir
        if model_filename:
            base = _os.path.join(model_dir, model_filename)
            if base.endswith(".pdmodel"):
                base = base[:-len(".pdmodel")]
        return _jit.load(base)


# -- checkpoint helpers (reference: fluid/dygraph/checkpoint.py) ----------
def save_dygraph(state_dict, model_path):
    """reference: checkpoint.py save_dygraph — .pdparams/.pdopt suffix
    chosen by content; optimizer state dicts always carry the '__step__'
    counter (optimizer/__init__.py state_dict)."""
    from ..framework.io import save as _save
    is_opt = "__step__" in state_dict or "LR_Scheduler" in state_dict
    suffix = ".pdopt" if is_opt else ".pdparams"
    _save(state_dict, model_path + suffix)


def load_dygraph(model_path):
    """reference: checkpoint.py load_dygraph -> (param_dict, opt_dict)."""
    import os as _os
    from ..framework.io import load as _load
    params = opt = None
    if _os.path.exists(model_path + ".pdparams"):
        params = _load(model_path + ".pdparams")
    if _os.path.exists(model_path + ".pdopt"):
        opt = _load(model_path + ".pdopt")
    if params is None and opt is None:
        raise ValueError(
            f"load_dygraph: neither {model_path}.pdparams nor .pdopt "
            "exists")
    return params, opt


# -- submodule layout parity (reference: fluid/dygraph/ is a package) -----
import sys as _sys
import types as _types


def _dy_submodule(name, **attrs):
    m = _types.ModuleType(f"{__name__}.{name}")
    for k, v in attrs.items():
        setattr(m, k, v)
    _sys.modules[m.__name__] = m
    globals()[name] = m
    return m


from ..core import autograd as _autograd  # noqa: E402
from .. import jit as _jit_mod  # noqa: E402
from .. import amp as _amp_mod  # noqa: E402
from ..framework import io as _fio  # noqa: E402
from ..nn.layer import rnn as _rnn_mod  # noqa: E402
from ..distributed import parallel as _par_mod  # noqa: E402

_dy_submodule("base", enable_dygraph=enable_dygraph,
              disable_dygraph=disable_dygraph, grad=grad,
              no_grad=no_grad, no_grad_=no_grad_,
              to_variable=to_variable, guard=guard, enabled=enabled)
_dy_submodule("nn", Linear=Linear, Embedding=Embedding, Conv2D=Conv2D,
              BatchNorm=BatchNorm, LayerNorm=LayerNorm, Dropout=Dropout,
              Pool2D=Pool2D, BilinearTensorProduct=BilinearTensorProduct,
              Conv2DTranspose=Conv2DTranspose, Conv3D=Conv3D,
              Conv3DTranspose=Conv3DTranspose, Flatten=Flatten,
              GroupNorm=GroupNorm, InstanceNorm=InstanceNorm,
              SpectralNorm=SpectralNorm, PRelu=PRelu, NCE=NCE,
              GRUUnit=GRUUnit, TreeConv=TreeConv)
_dy_submodule("container", Sequential=_Seq, ParameterList=ParameterList,
              LayerList=LayerList)
_dy_submodule("learning_rate_scheduler",
              LearningRateDecay=LearningRateDecay,
              ExponentialDecay=ExponentialDecay,
              InverseTimeDecay=InverseTimeDecay, LambdaDecay=LambdaDecay,
              MultiStepDecay=MultiStepDecay,
              NaturalExpDecay=NaturalExpDecay, NoamDecay=NoamDecay,
              PiecewiseDecay=PiecewiseDecay,
              PolynomialDecay=PolynomialDecay, StepDecay=StepDecay,
              CosineDecay=CosineDecay, LinearLrWarmup=LinearLrWarmup,
              ReduceLROnPlateau=ReduceLROnPlateau)
_dy_submodule("parallel", DataParallel=DataParallel,
              ParallelEnv=ParallelEnv,
              prepare_context=getattr(_par_mod, "prepare_context", None))
_dy_submodule("jit", save=_jit_mod.save, load=_jit_mod.load,
              to_static=_jit_mod.to_static, TracedLayer=TracedLayer)
_dy_submodule("amp", auto_cast=_amp_mod.auto_cast,
              amp_guard=_amp_mod.auto_cast,
              GradScaler=_amp_mod.GradScaler)
_dy_submodule("checkpoint", save_dygraph=save_dygraph,
              load_dygraph=load_dygraph)
_dy_submodule("io", save_dygraph=save_dygraph,
              load_dygraph=load_dygraph)
_dy_submodule("rnn", LSTMCell=_rnn_mod.LSTMCell,
              GRUCell=_rnn_mod.GRUCell)
_dy_submodule("tracer", Tracer=None)
_dy_submodule("layers", Layer=Layer)
_dy_submodule("dygraph_to_static",
              ProgramTranslator=ProgramTranslator)
_dy_submodule("static_runner", StaticModelRunner=StaticModelRunner)
