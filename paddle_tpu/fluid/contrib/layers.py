"""fluid.contrib.layers — the contrib op surface.

Reference parity: ``python/paddle/fluid/contrib/layers/nn.py`` (the
general-purpose subset: fused_elemwise_activation, fused_bn_add_act,
shuffle_batch, partial_concat, partial_sum, batch_fc) plus re-exports
of contrib names whose implementations live elsewhere in this
framework (sequence_topk_avg_pooling, tree_conv, sparse_embedding).

Real implementations include the CTR matching/tree ops
(match_matrix_tensor, tdm_child, tdm_sampler, rank_attention,
correlation, bilateral_slice — checked against the reference
unittests' numpy oracles / validation rules).  Only _pull_box_extended_sparse
(BoxPS hardware-coupled embedding pull) remains a raising stub.
"""
from __future__ import annotations

import numpy as np

from ...core.dispatch import ensure_tensor
from ...core import rng as rng_mod
from ... import ops
from ...nn import functional as F

__all__ = [
    "fused_elemwise_activation", "fused_bn_add_act", "shuffle_batch",
    "partial_concat", "partial_sum", "batch_fc",
    "match_matrix_tensor", "tdm_child", "tdm_sampler",
    "rank_attention", "correlation", "bilateral_slice",
    "var_conv_2d", "search_pyramid_hash",
    "sequence_topk_avg_pooling", "tree_conv", "sparse_embedding",
    "multiclass_nms2",
]


_BINARY = {"elementwise_add": ops.add, "elementwise_mul": ops.multiply}


def fused_elemwise_activation(x, y, functor_list, axis=-1, scale=0.0,
                              save_intermediate_out=True):
    """reference contrib/layers/nn.py:65 — Unary(Binary(x, y)) (or
    Binary(x, Unary(y))).  XLA fuses the chain anyway; the op exists for
    API parity."""
    x, y = ensure_tensor(x), ensure_tensor(y)
    if len(functor_list) != 2:
        raise ValueError("functor_list must have exactly two entries")
    a, b = functor_list
    if a in _BINARY:
        return _apply_unary(_BINARY[a](x, y), b, scale)
    if b in _BINARY:
        return _BINARY[b](x, _apply_unary(y, a, scale))
    raise ValueError(
        f"functor_list {functor_list}: one entry must be a binary "
        f"functor ({sorted(_BINARY)})")


def _apply_unary(t, name, scale):
    if name == "scale":
        return t * scale
    fn = getattr(F, name, None)
    if fn is None:
        raise ValueError(f"unknown unary functor {name!r}")
    return fn(t)


def fused_bn_add_act(x, y, momentum=0.9, epsilon=1e-5, param_attr=None,
                     bias_attr=None, moving_mean_name=None,
                     moving_variance_name=None, act="relu", name=None):
    """reference contrib/layers/nn.py fused_bn_add_act —
    act(batch_norm(x) + y); the reference fuses for cuDNN, XLA fuses
    the same chain automatically."""
    from ...static import nn as static_nn
    out = static_nn.batch_norm(x, momentum=momentum, epsilon=epsilon,
                               param_attr=param_attr,
                               bias_attr=bias_attr)
    out = out + ensure_tensor(y)
    return getattr(F, act)(out) if act else out


def shuffle_batch(x, seed=None):
    """reference contrib/layers/nn.py shuffle_batch — random permutation
    along dim 0 (CTR in-batch negative sampling)."""
    x = ensure_tensor(x)
    n = x.shape[0]
    from ...core.tensor import Tensor
    if seed is not None:
        perm = np.random.RandomState(int(seed)).permutation(n)
        return ops.gather(x, Tensor(perm.astype(np.int64)))
    import jax
    key = rng_mod.next_key()
    idx = jax.random.permutation(key, n)
    return ops.gather(x, Tensor(idx, stop_gradient=True))


def _partial_slices(inputs, start_index, length):
    outs = []
    for t in inputs:
        t = ensure_tensor(t)
        if len(t.shape) != 2:
            raise ValueError(
                "partial_concat/partial_sum support 2-D inputs only "
                "(reference: partial_concat_op.cc)")
        width = t.shape[1]
        start = start_index if start_index >= 0 else width + start_index
        stop = width if length < 0 else start + length
        outs.append(t[:, start:stop])
    return outs


def partial_concat(input, start_index=0, length=-1):
    """reference contrib/layers/nn.py:849 — slice each input's second
    dim [start, start+length) and concat along dim 1."""
    return ops.concat(_partial_slices(input, start_index, length),
                      axis=1)


def partial_sum(input, start_index=0, length=-1):
    """reference contrib/layers/nn.py partial_sum — same slicing,
    elementwise-summed."""
    outs = _partial_slices(input, start_index, length)
    total = outs[0]
    for t in outs[1:]:
        total = total + t
    return total


def batch_fc(input, param_size, param_attr=None, bias_size=None,
             bias_attr=None, act=None):
    """reference contrib/layers/nn.py:1381 — per-slot FC: input
    [B, M, K] @ w [B, K, N] + b [B, 1, N] (a batched matmul; the
    reference's custom CUDA kernel is one jnp.matmul here)."""
    from ...static.nn import _make_param
    from ...nn import initializer as I
    input = ensure_tensor(input)
    w = _make_param(list(param_size), "float32", param_attr,
                    I.XavierUniform(), "batch_fc_w")
    out = ops.matmul(input, w)
    if bias_size is not None:
        b = _make_param(list(bias_size), "float32", bias_attr,
                        I.Constant(0.0), "batch_fc_b")
        out = out + b
    return getattr(F, act)(out) if act else out


# -- re-exports: contrib names implemented elsewhere -----------------------

def sequence_topk_avg_pooling(input, row, col, topks, channel_num):
    from ...nn.functional.sequence import sequence_topk_avg_pooling as impl
    return impl(input, row, col, topks, channel_num)


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    from ..dygraph import TreeConv
    layer = TreeConv(int(nodes_vector.shape[-1]), output_size,
                     num_filters=num_filters, max_depth=max_depth,
                     act=act, param_attr=param_attr,
                     bias_attr=bias_attr, name=name)
    return layer(ensure_tensor(nodes_vector), ensure_tensor(edge_set))


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, param_attr=None, dtype="float32"):
    from ...static.nn import sparse_embedding as impl
    return impl(input, size, padding_idx=padding_idx,
                param_attr=param_attr, dtype=dtype)


def multiclass_nms2(bboxes, scores, score_threshold, nms_top_k,
                    keep_top_k, nms_threshold=0.3, normalized=True,
                    nms_eta=1.0, background_label=0,
                    return_index=False, name=None):
    """Returns the REFERENCE contract: Out, or (Out, Index) when
    ``return_index`` (Index = each kept detection's source row in
    ``bboxes``, padded -1).  Note this shim previously delegated to
    ``multiclass_nms`` and leaked its (Out, valid_count) pair for
    return_index=False; valid rows are now counted as
    ``(out[:, 0] >= 0).sum()``."""
    from ...vision.ops import multiclass_nms2 as impl
    return impl(bboxes, scores, score_threshold=score_threshold,
                nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                nms_threshold=nms_threshold, normalized=normalized,
                nms_eta=nms_eta, background_label=background_label,
                return_index=return_index)


def _ps_serving_stub(name):
    def fn(*args, **kwargs):
        raise NotImplementedError(
            f"fluid.contrib.layers.{name} belongs to the reference's "
            "parameter-server CTR serving stack (tree-based matching / "
            "pyramid hashing over distributed tables), which this "
            "framework's reduced PS scope does not include — see "
            "COVERAGE.md §2.3 'PS ops'")
    fn.__name__ = name
    return fn


for _n in ("_pull_box_extended_sparse",):
    globals()[_n] = _ps_serving_stub(_n)


def correlation(x, y, pad_size, kernel_size, max_displacement, stride1,
                stride2, corr_type_multiply=1):
    """reference contrib/layers/nn.py correlation (correlation_op.cu —
    the FlowNet cost-volume layer; CUDA-only there, one fused XLA
    program here, with the EXACT kernel geometry):

    * displacement grid: radius ``max_displacement // stride2``, step
      ``stride2`` (channel idx = row-disp-major, col-disp fastest);
    * output spatial size ``ceil((H + 2·pad − 2·(kernel_rad +
      max_displacement)) / stride1)`` with windows CENTERED at
      ``o·stride1 + max_displacement`` in padded coordinates;
    * every window divides by ``K²·C`` (pad zeros count — the kernel
      never truncates).
    """
    import math
    import jax.numpy as jnp
    from jax import lax
    from ...core.tensor import Tensor

    if corr_type_multiply != 1:
        raise NotImplementedError(
            "correlation: only corr_type_multiply=1 (the multiply form) "
            "is implemented — the reference CUDA kernel ignores other "
            "values too, but refusing beats silently diverging")
    if kernel_size % 2 == 0:
        raise ValueError(
            "correlation: kernel_size must be odd — the reference "
            "kernel's window is [-(K-1)//2, (K-1)//2], which for even K "
            "covers only (K-1)^2 taps while still dividing by K^2; "
            "refusing beats replicating that truncation silently")
    x = ensure_tensor(x)
    y = ensure_tensor(y)
    xa, ya = x._data, y._data
    if tuple(xa.shape) != tuple(ya.shape):
        raise ValueError(
            f"correlation: inputs must have identical shapes, got "
            f"{list(xa.shape)} vs {list(ya.shape)} (the reference op "
            "enforces the same)")
    B, C, H, W = xa.shape
    p, K, d = pad_size, kernel_size, max_displacement
    kernel_rad = (K - 1) // 2
    disp_rad = d // stride2
    Hp, Wp = H + 2 * p, W + 2 * p
    out_h = math.ceil((Hp - 2 * (kernel_rad + d)) / stride1)
    out_w = math.ceil((Wp - 2 * (kernel_rad + d)) / stride1)
    anchor = d - kernel_rad  # first window's top-left in padded coords
    if out_h <= 0 or out_w <= 0 or anchor < 0:
        raise ValueError(
            f"correlation: geometry is empty/out-of-bounds for H={H} "
            f"W={W} pad={p} kernel={K} max_displacement={d} — the "
            "reference kernel would read out of range here "
            f"(out={out_h}x{out_w}, first window offset {anchor})")
    xp = jnp.pad(xa, ((0, 0), (0, 0), (p, p), (p, p)))
    yp = jnp.pad(ya, ((0, 0), (0, 0), (p, p), (p, p)))
    # zero-filled shift workspace (roll would WRAP edge values)
    sh = disp_rad * stride2
    yp2 = jnp.pad(yp, ((0, 0), (0, 0), (sh, sh), (sh, sh)))
    outs = []
    denom = float(K * K * C)
    for tj in range(-disp_rad, disp_rad + 1):      # row displacement
        for ti in range(-disp_rad, disp_rad + 1):  # col displacement
            dy, dx = tj * stride2, ti * stride2
            shifted = yp2[:, :, sh + dy:sh + dy + Hp,
                          sh + dx:sh + dx + Wp]
            # channel-sum BEFORE the windowed reduction: the two sums
            # commute and this does 1/C of the window work
            prod = jnp.sum(xp * shifted, axis=1)   # [B, Hp, Wp]
            win = lax.reduce_window(
                prod, 0.0, lax.add, (1, K, K), (1, 1, 1), "valid")
            out_kl = win[:, anchor:anchor + out_h * stride1:stride1,
                         anchor:anchor + out_w * stride1:stride1] / denom
            outs.append(out_kl)
    stacked = jnp.stack(outs, axis=1)  # row-disp-major, col fastest
    return Tensor(stacked.astype(xa.dtype))


def match_matrix_tensor(x, y, channel_num, act=None, param_attr=None,
                        dtype="float32", name=None, x_lengths=None,
                        y_lengths=None, w_param=None):
    """reference contrib/layers/nn.py match_matrix_tensor
    (match_matrix_tensor_op.cc): per-pair bilinear match
    ``out[b, t] = x_b @ W_t @ y_b^T`` over ``channel_num`` channels.

    Dense+lengths convention (COVERAGE.md LoD reduction): ``x``
    [B, Lx, h], ``y`` [B, Ly, h]; positions beyond ``*_lengths`` are
    masked to zero.  Returns (out [B, channel_num, Lx, Ly],
    tmp [B, Lx, channel_num, h]) like the reference's (Out, Tmp)."""
    import jax.numpy as jnp
    from ...static.nn import _make_param
    from ...nn import initializer as I
    from ...core.tensor import Tensor

    x, y = ensure_tensor(x), ensure_tensor(y)
    h = x.shape[-1]
    w = ensure_tensor(w_param) if w_param is not None else _make_param(
        [h, channel_num, h], dtype, param_attr, I.XavierUniform(),
        "match_matrix_w")
    tmp = ops.einsum("blh,hck->blck", x, w)
    out = ops.einsum("blck,bmk->bclm", tmp, y)
    if x_lengths is not None:
        xl = ensure_tensor(x_lengths)._data.reshape(-1, 1)
        mx = (jnp.arange(x.shape[1])[None, :] < xl)
        out = out * Tensor(mx[:, None, :, None].astype(out._data.dtype))
    if y_lengths is not None:
        yl = ensure_tensor(y_lengths)._data.reshape(-1, 1)
        my = (jnp.arange(y.shape[1])[None, :] < yl)
        out = out * Tensor(my[:, None, None, :].astype(out._data.dtype))
    if act:
        out = getattr(F, act)(out)
    return out, tmp


def tdm_child(x, node_nums, child_nums, param_attr=None, dtype="int32",
              tree_info=None):
    # (dtype governs the tree table and outputs, like the reference)
    """reference contrib/layers/nn.py tdm_child (tdm_child_op.cc):
    gather each node's children + leaf mask from the tree table.

    ``tree_info`` [node_nums, 3 + child_nums] rows =
    [item_id, layer_id, parent, child_0..child_{n-1}]; node 0 is the
    null node.  Returns (child [..., child_nums],
    leaf_mask [..., child_nums] — 1 iff the child is a leaf, i.e. its
    item_id != 0).  ``tree_info`` may be passed directly (array) or
    created as a parameter via ``param_attr`` initializer like the
    reference."""
    import jax.numpy as jnp
    from ...core.tensor import Tensor
    from ...static.nn import _make_param
    from ...nn import initializer as I

    x = ensure_tensor(x)
    out_dtype = jnp.int64 if str(dtype) in ("int64", "paddle.int64") \
        else jnp.int32
    if tree_info is None:
        # integer storage: float32 would corrupt ids beyond 2^24
        info = _make_param([node_nums, 3 + child_nums], str(dtype),
                           param_attr, I.Constant(0.0), "tdm_tree_info")
        info_arr = info._data.astype(out_dtype)
    else:
        info_arr = ensure_tensor(tree_info)._data.astype(out_dtype)
    ids = x._data.astype(out_dtype)
    children = info_arr[ids, 3:3 + child_nums]          # [..., C]
    children = jnp.where((ids != 0)[..., None], children, 0)
    leaf_mask = (info_arr[children, 0] != 0)
    return (Tensor(children.astype(out_dtype)),
            Tensor(leaf_mask.astype(out_dtype)))


def rank_attention(input, rank_offset, rank_param_shape, rank_param_attr,
                   max_rank=3, max_size=0, rank_param=None):
    """reference contrib/layers/nn.py rank_attention
    (rank_attention_op.cu): CTR rank-pair attention — each instance with
    page-view rank ``l`` gathers, for every rank ``k`` present in its
    page view, the history instance at that rank and the weight block
    ``W[(l-1)·max_rank + (k-1)]``, then contracts.

    ``rank_offset`` [n, 1 + 2·max_rank] int: col 0 = 1-based instance
    rank (<=0 invalid); pairs (rank_k, row_index_k) follow.
    ``rank_param_shape`` = [max_rank² · d, out_col].  ``rank_param``
    may be passed directly for testing; otherwise created via attr."""
    import jax.numpy as jnp
    from ...core.tensor import Tensor
    from ...static.nn import _make_param
    from ...nn import initializer as I

    input = ensure_tensor(input)
    ro = ensure_tensor(rank_offset)._data.astype(jnp.int32)
    n, d = input.shape
    if rank_param is None:
        param = _make_param(list(rank_param_shape), "float32",
                            rank_param_attr, I.XavierUniform(),
                            "rank_attention_w")
    else:
        param = ensure_tensor(rank_param)
    pcol = param.shape[1]

    lower = ro[:, 0] - 1                      # [n]
    faster = ro[:, 1::2] - 1                  # [n, max_rank]
    index = ro[:, 2::2]                       # [n, max_rank]
    # ranks beyond max_rank carry no weight block — invalid like <=0
    valid = ((lower[:, None] >= 0) & (lower[:, None] < max_rank)
             & (faster >= 0) & (faster < max_rank))
    gathered = input._data[jnp.clip(index, 0, n - 1)]    # [n, K, d]
    gathered = jnp.where(valid[..., None], gathered, 0.0)
    # weight blocks [max_rank*max_rank, d, pcol]
    pblocks = param._data.reshape(max_rank * max_rank, d, pcol)
    sel = jnp.where(valid, lower[:, None] * max_rank + faster, 0)
    # invalid pairs already contribute zero: `gathered` is masked and
    # `sel` clamps to block 0 — no second mask over the big pb buffer
    pb = pblocks[sel]                                    # [n, K, d, pcol]
    out = jnp.einsum("nkd,nkdc->nc", gathered, pb)
    return Tensor(out.astype(input._data.dtype))


def tdm_sampler(x, neg_samples_num_list, layer_node_num_list,
                leaf_node_num, tree_travel_attr=None, tree_layer_attr=None,
                output_positive=True, output_list=True, seed=0,
                tree_dtype="int32", dtype="int32",
                travel=None, layer=None, name=None):
    """reference contrib/layers/nn.py tdm_sampler (tdm_sampler_op.cc):
    layer-wise negative sampling over a TDM tree.

    For each leaf item in ``x`` [B, 1] and each tree layer i: emit the
    item's ancestor on that layer (the positive, label 1, mask 0 when
    the travel entry is padding 0) plus ``neg_samples_num_list[i]``
    negatives drawn WITHOUT replacement from that layer's other nodes
    (label 0).  Sampling is host-side numpy — this op builds training
    DATA (it feeds the loader, like the reference's CPU-only kernel),
    so it is not traced.  ``travel`` [leaf_node_num, n_layers] and
    ``layer`` (flat node list) may be passed directly; otherwise they
    are created as parameters from the attrs like the reference."""
    from ...core.tensor import Tensor
    from ...static.nn import _make_param
    from ...nn import initializer as I

    n_layers = len(layer_node_num_list)
    if len(neg_samples_num_list) != n_layers:
        raise ValueError(
            "neg_samples_num_list and layer_node_num_list must have one "
            "entry per tree layer")
    x_np = np.asarray(ensure_tensor(x).numpy()).reshape(-1).astype(
        np.int64)
    if x_np.size and (x_np.min() < 0 or x_np.max() >= leaf_node_num):
        raise ValueError(
            f"tdm_sampler: leaf ids must be in [0, {leaf_node_num}) — "
            f"got range [{x_np.min()}, {x_np.max()}] (the reference "
            "kernel enforces the same bound)")
    if travel is None:
        travel = _make_param([leaf_node_num, n_layers], tree_dtype,
                             tree_travel_attr, I.Constant(0.0),
                             "tdm_travel")
    travel_np = np.asarray(ensure_tensor(travel).numpy()).astype(
        np.int64)
    if layer is None:
        layer = _make_param([sum(layer_node_num_list), 1],
                            tree_dtype, tree_layer_attr,
                            I.Constant(0.0), "tdm_layer")
    layer_np = np.asarray(ensure_tensor(layer).numpy()).reshape(-1) \
        .astype(np.int64)
    if len(layer_np) != sum(layer_node_num_list):
        raise ValueError(
            f"tdm_sampler: layer table has {len(layer_np)} nodes but "
            f"layer_node_num_list sums to {sum(layer_node_num_list)}")
    offs = np.cumsum([0] + list(layer_node_num_list))
    layers = [layer_np[offs[i]:offs[i + 1]] for i in range(n_layers)]
    for i, k in enumerate(neg_samples_num_list):
        if k >= layer_node_num_list[i]:
            raise ValueError(
                f"layer {i}: {k} negatives requested but the layer has "
                f"only {layer_node_num_list[i]} nodes (sampling is "
                "without replacement, excluding the positive)")

    rs = np.random.RandomState(seed)  # seed=0 IS a seed
    np_dtype = np.int64 if str(dtype) == "int64" else np.int32
    outs, labels, masks = [], [], []
    for i in range(n_layers):
        k = neg_samples_num_list[i]
        width = (1 if output_positive else 0) + k
        o = np.zeros((len(x_np), width), np_dtype)
        lab = np.zeros_like(o)
        msk = np.zeros_like(o)
        for b, leaf in enumerate(x_np):
            pos = int(travel_np[leaf, i])
            if pos == 0:
                continue  # padded travel: whole row stays 0/0/0
            cand = layers[i][layers[i] != pos]
            negs = rs.choice(cand, size=k, replace=False) if k else \
                np.empty(0, np.int64)
            row = ([pos] if output_positive else []) + list(negs)
            o[b, :len(row)] = row
            if output_positive:
                lab[b, 0] = 1
            msk[b, :len(row)] = 1
        outs.append(Tensor(o))
        labels.append(Tensor(lab))
        masks.append(Tensor(msk))
    if output_list:
        return outs, labels, masks

    def cat(ts):
        return Tensor(np.concatenate([t.numpy() for t in ts], axis=1))

    return cat(outs), cat(labels), cat(masks)


def bilateral_slice(x, guide, grid, has_offset=False, name=None):
    """reference contrib/layers/nn.py bilateral_slice
    (bilateral_slice_op.cu — HDRNet's guided bilateral-grid slicing;
    CUDA-only there, one fused XLA gather/lerp program here).

    ``x`` [B, Cin, H, W]; ``guide`` [B, H, W] in [0, 1); ``grid``
    [B, Cg, gd, gh, gw] with ``Cg = Cout·Cin`` (+``Cout`` when
    ``has_offset``).  Each pixel trilinearly samples an affine
    transform from the grid at (guide-depth, y, x) — tent weights with
    clamped corner indices, matching the reference kernel exactly —
    and applies it to the input channels."""
    import jax.numpy as jnp
    from ...core.tensor import Tensor

    x = ensure_tensor(x)
    guide = ensure_tensor(guide)
    grid = ensure_tensor(grid)
    xa, ga, gr = x._data, guide._data, grid._data
    B, Cin, H, W = xa.shape
    if tuple(ga.shape) != (B, H, W):
        raise ValueError(
            f"bilateral_slice: guide must be [B, H, W] = {[B, H, W]}, "
            f"got {list(ga.shape)}")
    if gr.ndim != 5 or gr.shape[0] != B:
        raise ValueError(
            f"bilateral_slice: grid must be [B, Cg, gd, gh, gw] with "
            f"batch {B}, got {list(gr.shape)}")
    _, Cg, gd, gh, gw = gr.shape
    stride = Cin + (1 if has_offset else 0)
    if Cg % stride:
        raise ValueError(
            f"bilateral_slice: grid channels ({Cg}) not divisible by "
            f"input_chans{'+1' if has_offset else ''} ({stride})")
    Cout = Cg // stride

    gx = (jnp.arange(W) + 0.5) * gw / W                  # [W]
    gy = (jnp.arange(H) + 0.5) * gh / H                  # [H]
    gz = ga * gd                                         # [B, H, W]
    gxb = jnp.broadcast_to(gx[None, None, :], (B, H, W))
    gyb = jnp.broadcast_to(gy[None, :, None], (B, H, W))

    fx = jnp.floor(gxb - 0.5)
    fy = jnp.floor(gyb - 0.5)
    fz = jnp.floor(gz - 0.5)

    coeff = jnp.zeros((B, H, W, Cg), jnp.float32)
    bidx = jnp.arange(B)[:, None, None]
    for dz in (0, 1):
        zz = fz + dz
        z_ = jnp.clip(zz, 0, gd - 1).astype(jnp.int32)
        wz = jnp.maximum(1.0 - jnp.sqrt((zz + 0.5 - gz) ** 2 + 1e-8),
                         0.0)
        for dy in (0, 1):
            yy = fy + dy
            y_ = jnp.clip(yy, 0, gh - 1).astype(jnp.int32)
            wy = jnp.maximum(1.0 - jnp.abs(yy + 0.5 - gyb), 0.0)
            for dx in (0, 1):
                xx = fx + dx
                x_ = jnp.clip(xx, 0, gw - 1).astype(jnp.int32)
                wx = jnp.maximum(1.0 - jnp.abs(xx + 0.5 - gxb), 0.0)
                corner = gr[bidx, :, z_, y_, x_]     # [B, H, W, Cg]
                coeff = coeff + corner * (wx * wy * wz)[..., None]

    coeff = coeff.reshape(B, H, W, Cout, stride)
    xin = jnp.moveaxis(xa, 1, -1)                        # [B, H, W, Cin]
    out = jnp.einsum("bhwoc,bhwc->bhwo", coeff[..., :Cin], xin)
    if has_offset:
        out = out + coeff[..., Cin]
    return Tensor(jnp.moveaxis(out, -1, 1).astype(xa.dtype))


def var_conv_2d(x, row_lengths, col_lengths, input_channel,
                output_channel, filter_size, stride=1, param_attr=None,
                act=None, dtype="float32", name=None, w_param=None):
    """reference contrib/layers/nn.py var_conv_2d (var_conv_2d_op.cc):
    per-sample 2-D conv over VARIABLE H_i x W_i feature maps.

    Dense+lengths redesign of the LoD original (COVERAGE.md reduction):
    ``x`` [B, C, Hmax, Wmax] with per-sample ``row_lengths``/
    ``col_lengths``; windows are centered (pad K//2, exactly the
    reference's half-kernel anchoring) and read ZEROS beyond a sample's
    own bounds — masking the canvas makes the batched conv equal the
    reference's per-sample im2col, because zero pixels contribute
    nothing.  Output [B, out_ch, ceil(Hmax/s), ceil(Wmax/s)], zeroed
    beyond each sample's ceil(h_i/s) x ceil(w_i/s) region.  Weight
    layout follows the reference: [out_ch, C*Kh*Kw] in (c, ky, kx)
    order."""
    import jax.numpy as jnp
    from jax import lax
    from ...core.tensor import Tensor
    from ...static.nn import _make_param
    from ...nn import initializer as I

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    kh, kw = _pair(filter_size)
    sh, sw = _pair(stride)
    x = ensure_tensor(x)
    xa = x._data
    B, C, H, W = xa.shape
    if C != input_channel:
        raise ValueError(
            f"var_conv_2d: x has {C} channels, input_channel says "
            f"{input_channel}")
    rl = ensure_tensor(row_lengths)._data.reshape(-1)
    cl_ = ensure_tensor(col_lengths)._data.reshape(-1)
    if rl.shape[0] != B or cl_.shape[0] != B:
        raise ValueError(
            f"var_conv_2d: row_lengths/col_lengths must have one entry "
            f"per sample (batch {B}), got {rl.shape[0]}/{cl_.shape[0]}")
    if w_param is not None:
        w = ensure_tensor(w_param)
    else:
        w = _make_param([output_channel, C * kh * kw], dtype, param_attr,
                        I.XavierUniform(), "var_conv_w")
    w4 = w._data.reshape(output_channel, C, kh, kw)

    # zero beyond each sample's own extent: the conv then reads zeros
    # exactly where the reference's bounds check skips
    ri = jnp.arange(H)[None, :, None]
    ci = jnp.arange(W)[None, None, :]
    valid = (ri < rl[:, None, None]) & (ci < cl_[:, None, None])
    xm = xa * valid[:, None, :, :].astype(xa.dtype)

    out_h = -(-H // sh)
    out_w = -(-W // sw)
    lo_h, lo_w = kh // 2, kw // 2
    hi_h = max(0, (out_h - 1) * sh + kh - lo_h - H)
    hi_w = max(0, (out_w - 1) * sw + kw - lo_w - W)
    acc = lax.conv_general_dilated(
        xm, w4, (sh, sw), ((lo_h, hi_h), (lo_w, hi_w)))
    # zero beyond each sample's ceil(h_i/s) x ceil(w_i/s) output region
    orow = -(-rl // sh)
    ocol = -(-cl_ // sw)
    ro = jnp.arange(out_h)[None, :, None]
    co = jnp.arange(out_w)[None, None, :]
    ovalid = (ro < orow[:, None, None]) & (co < ocol[:, None, None])
    out = acc * ovalid[:, None, :, :].astype(acc.dtype)
    out_t = Tensor(out)
    return getattr(F, act)(out_t) if act else out_t


def search_pyramid_hash(input, num_emb, space_len, pyramid_layer, rand_len,
                        drop_out_percent, is_training, use_filter,
                        white_list_len, black_list_len, seed, lr,
                        param_attr=None, param_attr_wl=None,
                        param_attr_bl=None, name=None,
                        distribute_update_vars=None, lengths=None,
                        weights=None):
    """reference contrib/layers/nn.py search_pyramid_hash
    (pyramid_hash_op.cc): hash-embedding of every 2..pyramid_layer-gram.

    Exact kernel semantics: ids are converted to float32 and each
    n-gram's RAW BYTES are XXH32-hashed once per rand_len-chunk
    (chunk at offset j uses hash seed j, modulo space_len) to index a
    contiguous slice of the weight table; every surviving n-gram emits
    one embedding row.  Host-side numpy+xxhash by design — this is a
    data-prep op like tdm_sampler (the reference kernel is CPU-only).

    Dense+lengths convention: ``input`` [B, L] int32 with optional
    ``lengths`` [B]; returns (emb [B, M, num_emb] zero-padded,
    kept_counts [B]) — a sequence with no surviving n-gram contributes
    one ZERO row, exactly like the reference's LoD output.  Training
    dropout keeps each n-gram with prob 1-drop_out_percent (numpy RNG;
    the reference uses rand_r, so the MASK differs while eval output is
    bit-exact).  ``use_filter=True`` (bloom white/black lists stored as
    binary blobs) is out of scope and raises."""
    import numpy as np
    import xxhash
    from ...core.tensor import Tensor
    from ...static.nn import _make_param
    from ...nn import initializer as I

    if use_filter:
        raise NotImplementedError(
            "search_pyramid_hash(use_filter=True): bloom-filter white/"
            "black lists are binary blobs of the reference's PS stack; "
            "filterless hashing is supported")
    if num_emb % rand_len:
        raise ValueError(
            f"search_pyramid_hash: num_emb ({num_emb}) must be a "
            f"multiple of rand_len ({rand_len}) — the kernel copies "
            "rand_len-sized chunks")
    x = ensure_tensor(input)
    ids = np.asarray(x.numpy()).astype(np.int32)
    if ids.ndim == 1:
        ids = ids[None, :]
    B, L = ids.shape
    lens = (np.asarray(ensure_tensor(lengths).numpy()).reshape(-1)
            if lengths is not None else np.full(B, L))
    if lens.size and (lens.min() < 0 or lens.max() > L):
        raise ValueError(
            f"search_pyramid_hash: lengths must be in [0, {L}] "
            f"(the padded width), got range [{lens.min()}, "
            f"{lens.max()}]")
    if weights is not None:
        w_np = np.asarray(ensure_tensor(weights).numpy())
    else:
        w = _make_param([space_len + rand_len, 1], "float32", param_attr,
                        I.XavierUniform(), "pyramid_hash_w")
        w_np = np.asarray(w.numpy())
    w_flat = w_np.reshape(-1).astype(np.float32)
    if len(w_flat) < space_len + rand_len:
        raise ValueError(
            f"search_pyramid_hash: weight table needs space_len + "
            f"rand_len = {space_len + rand_len} entries, got "
            f"{len(w_flat)} (chunks are read CONTIGUOUSLY from the "
            "hashed position)")

    rs = np.random.RandomState(seed)  # seed=0 IS a seed
    per_seq = []
    for b in range(B):
        w_len = int(lens[b])
        fids = ids[b, :w_len].astype(np.float32)
        rows = []
        if w_len >= 2:
            for ilayer in range(1, min(pyramid_layer, w_len)):
                for l in range(w_len - ilayer):
                    if is_training and \
                            rs.rand() < drop_out_percent:
                        continue
                    gram = fids[l:l + ilayer + 1].tobytes()
                    emb = np.empty(num_emb, np.float32)
                    for j in range(0, num_emb, rand_len):
                        pos = xxhash.xxh32(gram, seed=j).intdigest() \
                            % space_len
                        emb[j:j + rand_len] = w_flat[pos:pos + rand_len]
                    rows.append(emb)
        if not rows:
            rows = [np.zeros(num_emb, np.float32)]
        per_seq.append(np.stack(rows))
    counts = np.array([len(r) for r in per_seq], np.int64)
    M = counts.max() if counts.size else 0
    out = np.zeros((B, int(M), num_emb), np.float32)
    for b, r in enumerate(per_seq):
        out[b, :len(r)] = r
    return Tensor(out), Tensor(counts)
