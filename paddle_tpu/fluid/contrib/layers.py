"""fluid.contrib.layers — the contrib op surface.

Reference parity: ``python/paddle/fluid/contrib/layers/nn.py`` (the
general-purpose subset: fused_elemwise_activation, fused_bn_add_act,
shuffle_batch, partial_concat, partial_sum, batch_fc) plus re-exports
of contrib names whose implementations live elsewhere in this
framework (sequence_topk_avg_pooling, tree_conv, sparse_embedding).

The CTR-serving long tail (tdm_child/tdm_sampler, search_pyramid_hash,
rank_attention, var_conv_2d, match_matrix_tensor, bilateral_slice,
correlation, _pull_box_extended_sparse) is tied to the reference's
parameter-server serving stack and is NOT implemented; calling them
raises with that scope note rather than silently degrading.
"""
from __future__ import annotations

import numpy as np

from ...core.dispatch import ensure_tensor
from ...core import rng as rng_mod
from ... import ops
from ...nn import functional as F

__all__ = [
    "fused_elemwise_activation", "fused_bn_add_act", "shuffle_batch",
    "partial_concat", "partial_sum", "batch_fc",
    "sequence_topk_avg_pooling", "tree_conv", "sparse_embedding",
    "multiclass_nms2",
]


_BINARY = {"elementwise_add": ops.add, "elementwise_mul": ops.multiply}


def fused_elemwise_activation(x, y, functor_list, axis=-1, scale=0.0,
                              save_intermediate_out=True):
    """reference contrib/layers/nn.py:65 — Unary(Binary(x, y)) (or
    Binary(x, Unary(y))).  XLA fuses the chain anyway; the op exists for
    API parity."""
    x, y = ensure_tensor(x), ensure_tensor(y)
    if len(functor_list) != 2:
        raise ValueError("functor_list must have exactly two entries")
    a, b = functor_list
    if a in _BINARY:
        return _apply_unary(_BINARY[a](x, y), b, scale)
    if b in _BINARY:
        return _BINARY[b](x, _apply_unary(y, a, scale))
    raise ValueError(
        f"functor_list {functor_list}: one entry must be a binary "
        f"functor ({sorted(_BINARY)})")


def _apply_unary(t, name, scale):
    if name == "scale":
        return t * scale
    fn = getattr(F, name, None)
    if fn is None:
        raise ValueError(f"unknown unary functor {name!r}")
    return fn(t)


def fused_bn_add_act(x, y, momentum=0.9, epsilon=1e-5, param_attr=None,
                     bias_attr=None, moving_mean_name=None,
                     moving_variance_name=None, act="relu", name=None):
    """reference contrib/layers/nn.py fused_bn_add_act —
    act(batch_norm(x) + y); the reference fuses for cuDNN, XLA fuses
    the same chain automatically."""
    from ...static import nn as static_nn
    out = static_nn.batch_norm(x, momentum=momentum, epsilon=epsilon,
                               param_attr=param_attr,
                               bias_attr=bias_attr)
    out = out + ensure_tensor(y)
    return getattr(F, act)(out) if act else out


def shuffle_batch(x, seed=None):
    """reference contrib/layers/nn.py shuffle_batch — random permutation
    along dim 0 (CTR in-batch negative sampling)."""
    x = ensure_tensor(x)
    n = x.shape[0]
    from ...core.tensor import Tensor
    if seed is not None:
        perm = np.random.RandomState(int(seed)).permutation(n)
        return ops.gather(x, Tensor(perm.astype(np.int64)))
    import jax
    key = rng_mod.next_key()
    idx = jax.random.permutation(key, n)
    return ops.gather(x, Tensor(idx, stop_gradient=True))


def _partial_slices(inputs, start_index, length):
    outs = []
    for t in inputs:
        t = ensure_tensor(t)
        if len(t.shape) != 2:
            raise ValueError(
                "partial_concat/partial_sum support 2-D inputs only "
                "(reference: partial_concat_op.cc)")
        width = t.shape[1]
        start = start_index if start_index >= 0 else width + start_index
        stop = width if length < 0 else start + length
        outs.append(t[:, start:stop])
    return outs


def partial_concat(input, start_index=0, length=-1):
    """reference contrib/layers/nn.py:849 — slice each input's second
    dim [start, start+length) and concat along dim 1."""
    return ops.concat(_partial_slices(input, start_index, length),
                      axis=1)


def partial_sum(input, start_index=0, length=-1):
    """reference contrib/layers/nn.py partial_sum — same slicing,
    elementwise-summed."""
    outs = _partial_slices(input, start_index, length)
    total = outs[0]
    for t in outs[1:]:
        total = total + t
    return total


def batch_fc(input, param_size, param_attr=None, bias_size=None,
             bias_attr=None, act=None):
    """reference contrib/layers/nn.py:1381 — per-slot FC: input
    [B, M, K] @ w [B, K, N] + b [B, 1, N] (a batched matmul; the
    reference's custom CUDA kernel is one jnp.matmul here)."""
    from ...static.nn import _make_param
    from ...nn import initializer as I
    input = ensure_tensor(input)
    w = _make_param(list(param_size), "float32", param_attr,
                    I.XavierUniform(), "batch_fc_w")
    out = ops.matmul(input, w)
    if bias_size is not None:
        b = _make_param(list(bias_size), "float32", bias_attr,
                        I.Constant(0.0), "batch_fc_b")
        out = out + b
    return getattr(F, act)(out) if act else out


# -- re-exports: contrib names implemented elsewhere -----------------------

def sequence_topk_avg_pooling(input, row, col, topks, channel_num):
    from ...nn.functional.sequence import sequence_topk_avg_pooling as impl
    return impl(input, row, col, topks, channel_num)


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    from ..dygraph import TreeConv
    layer = TreeConv(int(nodes_vector.shape[-1]), output_size,
                     num_filters=num_filters, max_depth=max_depth,
                     act=act, param_attr=param_attr,
                     bias_attr=bias_attr, name=name)
    return layer(ensure_tensor(nodes_vector), ensure_tensor(edge_set))


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, param_attr=None, dtype="float32"):
    from ...static.nn import sparse_embedding as impl
    return impl(input, size, padding_idx=padding_idx,
                param_attr=param_attr, dtype=dtype)


def multiclass_nms2(bboxes, scores, score_threshold, nms_top_k,
                    keep_top_k, nms_threshold=0.3, normalized=True,
                    nms_eta=1.0, background_label=0,
                    return_index=False, name=None):
    if return_index:
        raise NotImplementedError(
            "multiclass_nms2(return_index=True): the XLA-shaped nms "
            "returns padded [keep_top_k, 6] rows without source indices")
    from ...vision.ops import multiclass_nms as impl
    return impl(bboxes, scores, score_threshold=score_threshold,
                nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                nms_threshold=nms_threshold, normalized=normalized,
                nms_eta=nms_eta, background_label=background_label)


def _ps_serving_stub(name):
    def fn(*args, **kwargs):
        raise NotImplementedError(
            f"fluid.contrib.layers.{name} belongs to the reference's "
            "parameter-server CTR serving stack (tree-based matching / "
            "pyramid hashing over distributed tables), which this "
            "framework's reduced PS scope does not include — see "
            "COVERAGE.md §2.3 'PS ops'")
    fn.__name__ = name
    return fn


for _n in ("tdm_child", "tdm_sampler", "search_pyramid_hash",
           "rank_attention", "var_conv_2d", "match_matrix_tensor",
           "bilateral_slice", "correlation",
           "_pull_box_extended_sparse"):
    globals()[_n] = _ps_serving_stub(_n)
