"""1.x import path for the quantization subsystem (reference:
fluid/contrib/slim/quantization/imperative/qat.py) — the implementation
lives in paddle_tpu.quantization."""
from paddle_tpu.quantization import (  # noqa: F401
    ImperativeQuantAware, ImperativeCalcOutScale,
    FakeQuantAbsMax, FakeQuantMovingAverage,
    QuantizedLinear, QuantizedConv2D, MovingAverageAbsMaxScale,
)
from paddle_tpu.quantization import PostTrainingQuantization  # noqa: F401
