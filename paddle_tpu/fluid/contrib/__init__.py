"""fluid.contrib namespace (reference: python/paddle/fluid/contrib/)."""
from . import slim  # noqa: F401
from . import layers  # noqa: F401
