"""``fluid.nets`` — composite network builders.

Reference parity: ``python/paddle/fluid/nets.py`` (simple_img_conv_pool,
img_conv_group, sequence_conv_pool, glu, scaled_dot_product_attention) —
pure compositions of layers, reimplemented over the modern builders.
"""
from __future__ import annotations


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1,
                         conv_padding=0, conv_dilation=1, conv_groups=1,
                         param_attr=None, bias_attr=None, act=None,
                         use_cudnn=True):
    """reference: fluid/nets.py simple_img_conv_pool."""
    from ..static.nn import conv2d
    from ..nn import functional as F
    conv = conv2d(input, num_filters=num_filters, filter_size=filter_size,
                  stride=conv_stride, padding=conv_padding,
                  dilation=conv_dilation, groups=conv_groups,
                  param_attr=param_attr, bias_attr=bias_attr, act=act)
    return F.pool2d(conv, pool_size=pool_size, pool_type=pool_type,
                    pool_stride=pool_stride, pool_padding=pool_padding,
                    global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """reference: fluid/nets.py img_conv_group (VGG-style conv stack)."""
    from ..static.nn import conv2d, batch_norm, dropout
    from ..nn import functional as F

    def expand(v, n):
        return v if isinstance(v, (list, tuple)) else [v] * n

    n = len(conv_num_filter)
    paddings = expand(conv_padding, n)
    fsizes = expand(conv_filter_size, n)
    with_bn = expand(conv_with_batchnorm, n)
    drops = expand(conv_batchnorm_drop_rate, n)
    attrs = param_attr if isinstance(param_attr, (list, tuple)) \
        else [param_attr] * n
    tmp = input
    for i in range(n):
        tmp = conv2d(tmp, num_filters=conv_num_filter[i],
                     filter_size=fsizes[i], padding=paddings[i],
                     param_attr=attrs[i],
                     act=None if with_bn[i] else conv_act)
        if with_bn[i]:
            tmp = batch_norm(tmp, act=conv_act)
            if drops[i] > 0:
                tmp = dropout(tmp, dropout_prob=drops[i])
    return F.pool2d(tmp, pool_size=pool_size, pool_stride=pool_stride,
                    pool_type=pool_type)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None,
                       lengths=None):
    """reference: fluid/nets.py sequence_conv_pool — context conv over
    time then sequence pooling.  Dense form: input [B, T, D] + lengths;
    weights created here like the reference's param_attr path."""
    import numpy as np
    from ..core.tensor import Parameter
    from ..nn import functional as F
    from ..core.dispatch import ensure_tensor
    x = ensure_tensor(input)
    d = int(x.shape[-1])
    rng = np.random.RandomState(0)
    bound = 1.0 / np.sqrt(filter_size * d)
    w = Parameter(rng.uniform(-bound, bound,
                              (filter_size * d, num_filters)).astype(
                                  "float32"))
    conv = F.sequence_conv(x, w, context_length=filter_size,
                           lengths=lengths)
    if act is not None:
        conv = getattr(F, act)(conv)
    return F.sequence_pool(conv, pool_type, lengths=lengths)


def glu(input, dim=-1):
    """reference: fluid/nets.py glu — gated linear unit split."""
    from ..nn import functional as F
    return F.glu(input, axis=dim)


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """reference: fluid/nets.py scaled_dot_product_attention ([B, S, D]
    inputs, multi-head internally)."""
    from ..nn import functional as F
    from ..ops.manipulation import reshape
    from ..core.dispatch import ensure_tensor
    q = ensure_tensor(queries)
    k = ensure_tensor(keys)
    v = ensure_tensor(values)
    b, sq, d = [int(s) for s in q.shape]
    sk = int(k.shape[1])
    dv = int(v.shape[-1])
    if d % num_heads or dv % num_heads:
        raise ValueError(
            f"hidden sizes ({d}, {dv}) must divide num_heads {num_heads}")
    qh = reshape(q, [b, sq, num_heads, d // num_heads])
    kh = reshape(k, [b, sk, num_heads, d // num_heads])
    vh = reshape(v, [b, sk, num_heads, dv // num_heads])
    out = F.scaled_dot_product_attention(qh, kh, vh,
                                         dropout_p=dropout_rate)
    return reshape(out, [b, sq, dv])
