"""``fluid.io`` — the 1.x save/load + reader surface.

Reference parity: ``python/paddle/fluid/io.py`` (save/load_params,
save/load_persistables, save/load_vars, save/load_inference_model,
program-state helpers, ``batch``) plus the Dataset/DataLoader re-exports
the reference module carries.  Persistable state here is the static
Program's parameter dict (static/program.py), so every variant below is a
view over the same dict-save machinery.
"""
from __future__ import annotations

import os

from ..io import *  # noqa: F401,F403  (full paddle.io surface: loaders,
#                      samplers, dataset combinators, DataFeeder, native
#                      dataset engine — the reference fluid.io re-exports
#                      the reader stack the same way)
from ..io import DataFeeder, DatasetFactory  # noqa: F401
from ..io import InMemoryDataset, QueueDataset  # noqa: F401
from ..static.io import (  # noqa: F401
    save_inference_model as _save_inference_model,
    load_inference_model as _load_inference_model)
from ..static.compat import (  # noqa: F401
    save_vars, load_vars, set_program_state, load_program_state)
from ..static.executor import save as _program_save
from ..static.executor import load as _program_load


def batch(reader, batch_size, drop_last=False):
    """1.x reader decorator: group a sample generator into batches
    (reference: fluid/io.py batch)."""

    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


def save(program, model_path, protocol=4, **configs):
    return _program_save(program, model_path, protocol)


def load(program, model_path, executor=None, var_list=None):
    return _program_load(program, model_path)


def save_params(executor, dirname, main_program=None, filename=None):
    """reference: fluid/io.py save_params (static captures hold exactly
    the program's parameters here, so params == persistables)."""
    return save_vars(executor, dirname, main_program, filename=filename)


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program, filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    """reference: fluid/io.py:621 — every persistable var (params +
    optimizer state)."""
    return save_vars(executor, dirname, main_program, filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program, filename=filename)


def get_program_parameter(program):
    """reference: fluid/io.py get_program_parameter."""
    state = getattr(program, "state_dict", None)
    if state is None:
        return []
    return list(program.state_dict().keys())


def get_program_persistable_vars(program):
    return get_program_parameter(program)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, **kwargs):
    """1.x signature (reference: fluid/io.py:1199) over the modern
    static.io exporter: dirname becomes the artifact prefix."""
    prefix = os.path.join(dirname, model_filename or "model")
    if prefix.endswith(".pdmodel"):
        prefix = prefix[:-len(".pdmodel")]
    from ..static import default_main_program
    program = main_program or default_main_program()
    feed_vars = [program.var(n) if hasattr(program, "var") else n
                 for n in feeded_var_names]
    return _save_inference_model(prefix, feed_vars, target_vars,
                                 executor=executor, program=program)


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None, **kwargs):
    prefix = os.path.join(dirname, model_filename or "model")
    if prefix.endswith(".pdmodel"):
        prefix = prefix[:-len(".pdmodel")]
    return _load_inference_model(prefix, executor=executor)
