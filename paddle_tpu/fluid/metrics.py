"""``fluid.metrics`` — the 1.x streaming metric classes.

Reference parity: ``python/paddle/fluid/metrics.py`` (MetricBase,
CompositeMetric, Precision, Recall, Accuracy, ChunkEvaluator,
EditDistance, DetectionMAP, Auc).  These are host-side accumulators fed
with numpy batches; chunk extraction mirrors ``chunk_eval_op.cc`` and the
mAP computation ``detection_map_op.cc`` (integral + 11-point modes).
"""
from __future__ import annotations

import numpy as np


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError

    def get_config(self):
        return {"name": self._name}


class CompositeMetric(MetricBase):
    """Bundle several metrics sharing update arguments (reference
    :CompositeMetric)."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        if not isinstance(metric, MetricBase):
            raise ValueError("add_metric expects a MetricBase")
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds=preds, labels=labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    """Binary precision over 0/1 preds (reference :Precision)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def eval(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def eval(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0


class Accuracy(MetricBase):
    """Weighted streaming accuracy (reference :Accuracy — fed with the
    accuracy op's minibatch value)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        if weight < 0:
            raise ValueError("weight must be nonnegative")
        self.value += float(np.asarray(value).mean()) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy: no batches accumulated")
        return self.value / self.weight


def extract_chunks(tags, chunk_scheme, num_chunk_types,
                   excluded_chunk_types=None):
    """Decode (begin, end, type) spans from a tag sequence
    (reference: chunk_eval_op.cc tag layouts).

    Tag id layout per scheme (type-major):
      IOB:   type*2 + {B:0, I:1}
      IOE:   type*2 + {I:0, E:1}
      IOBES: type*4 + {B:0, I:1, E:2, S:3}
      plain: tag == type
    The 'outside' tag is the largest id (num_chunk_types * tag_num).
    """
    excluded = set(excluded_chunk_types or [])
    scheme = chunk_scheme.upper()
    n_pos = {"IOB": 2, "IOE": 2, "IOBES": 4, "PLAIN": 1}[scheme]
    outside = num_chunk_types * n_pos
    chunks = []
    start = None
    cur_type = None

    def close(end):
        nonlocal start, cur_type
        if start is not None and cur_type not in excluded:
            chunks.append((start, end, cur_type))
        start, cur_type = None, None

    for i, tag in enumerate(list(tags) + [outside]):
        tag = int(tag)
        if tag >= outside or tag < 0:
            close(i)
            continue
        ctype, pos = divmod(tag, n_pos)
        if scheme == "PLAIN":
            if cur_type != ctype:
                close(i)
                start, cur_type = i, ctype
        elif scheme == "IOB":
            if pos == 0 or cur_type != ctype:  # B or type switch
                close(i)
                start, cur_type = i, ctype
        elif scheme == "IOE":
            if cur_type != ctype:
                close(i)
                start, cur_type = i, ctype
            if pos == 1:  # E ends the chunk inclusively
                close(i + 1)
        else:  # IOBES
            if pos == 0:  # B
                close(i)
                start, cur_type = i, ctype
            elif pos == 1:  # I
                if cur_type != ctype:
                    close(i)
                    start, cur_type = i, ctype
            elif pos == 2:  # E
                if cur_type != ctype:
                    close(i)
                    start, cur_type = i, ctype
                close(i + 1)
            else:  # S: single-token chunk
                close(i)
                if ctype not in excluded:
                    chunks.append((i, i + 1, ctype))
    return chunks


def chunk_count(infer, label, chunk_scheme, num_chunk_types,
                excluded_chunk_types=None, lengths=None):
    """(num_infer, num_label, num_correct) chunk counts for a batch of
    tag rows (the chunk_eval op's outputs)."""
    infer = np.asarray(infer)
    label = np.asarray(label)
    if infer.ndim == 1:
        infer, label = infer[None], label[None]
    n_inf = n_lab = n_cor = 0
    for i in range(infer.shape[0]):
        ln = int(lengths[i]) if lengths is not None else infer.shape[1]
        ci = extract_chunks(infer[i, :ln], chunk_scheme, num_chunk_types,
                            excluded_chunk_types)
        cl = extract_chunks(label[i, :ln], chunk_scheme, num_chunk_types,
                            excluded_chunk_types)
        n_inf += len(ci)
        n_lab += len(cl)
        n_cor += len(set(ci) & set(cl))
    return n_inf, n_lab, n_cor


class ChunkEvaluator(MetricBase):
    """Streaming chunk precision/recall/F1 (reference :ChunkEvaluator;
    counts via chunk_count above, the chunk_eval op analogue)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).sum())
        self.num_label_chunks += int(np.asarray(num_label_chunks).sum())
        self.num_correct_chunks += int(
            np.asarray(num_correct_chunks).sum())

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


class EditDistance(MetricBase):
    """Streaming average edit distance + instance error rate
    (reference :EditDistance fed by the edit_distance op)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num=None):
        d = np.asarray(distances, np.float64).reshape(-1)
        self.total_distance += float(d.sum())
        self.seq_num += int(seq_num if seq_num is not None else len(d))
        self.instance_error += int((d > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("EditDistance: no data added")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class DetectionMAP(MetricBase):
    """Mean average precision for detection (reference :DetectionMAP /
    detection_map_op.cc).  update() takes per-image detections
    [[label, score, x1, y1, x2, y2], ...] and ground truths
    [[label, x1, y1, x2, y2], ...] (+ optional difficult flags)."""

    def __init__(self, name=None, overlap_threshold=0.5,
                 evaluate_difficult=False, ap_version="integral"):
        super().__init__(name)
        if ap_version not in ("integral", "11point"):
            raise ValueError(f"unknown ap_version {ap_version}")
        self.overlap_threshold = overlap_threshold
        self.evaluate_difficult = evaluate_difficult
        self.ap_version = ap_version
        self.reset()

    def reset(self):
        self._dets = []   # (img_id, label, score, box)
        self._gts = []    # (img_id, label, box, difficult)
        self._img = 0

    def update(self, detections, gt_boxes, difficult=None):
        detections = np.asarray(detections, np.float64).reshape(-1, 6)
        gt_boxes = np.asarray(gt_boxes, np.float64).reshape(-1, 5)
        if difficult is None:
            difficult = np.zeros(len(gt_boxes), bool)
        else:
            difficult = np.asarray(difficult).astype(bool).reshape(-1)
        for d in detections:
            self._dets.append((self._img, int(d[0]), float(d[1]), d[2:6]))
        for g, hard in zip(gt_boxes, difficult):
            self._gts.append((self._img, int(g[0]), g[1:5], bool(hard)))
        self._img += 1

    @staticmethod
    def _iou(a, b):
        ix1, iy1 = max(a[0], b[0]), max(a[1], b[1])
        ix2, iy2 = min(a[2], b[2]), min(a[3], b[3])
        iw, ih = max(ix2 - ix1, 0.0), max(iy2 - iy1, 0.0)
        inter = iw * ih
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    def _average_precision(self, recall, precision):
        if self.ap_version == "11point":
            ap = 0.0
            for t in np.linspace(0, 1, 11):
                mask = recall >= t
                ap += (precision[mask].max() if mask.any() else 0.0) / 11
            return ap
        # integral (VOC-style): sum precision over recall increments
        mrec = np.concatenate([[0.0], recall, [1.0]])
        mpre = np.concatenate([[0.0], precision, [0.0]])
        for i in range(len(mpre) - 2, -1, -1):
            mpre[i] = max(mpre[i], mpre[i + 1])
        idx = np.where(mrec[1:] != mrec[:-1])[0]
        return float(((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]).sum())

    def eval(self):
        labels = sorted({g[1] for g in self._gts})
        aps = []
        for cls in labels:
            gts = [g for g in self._gts if g[1] == cls]
            if not self.evaluate_difficult:
                n_pos = sum(1 for g in gts if not g[3])
            else:
                n_pos = len(gts)
            dets = sorted((d for d in self._dets if d[1] == cls),
                          key=lambda d: -d[2])
            matched = set()
            tp = np.zeros(len(dets))
            fp = np.zeros(len(dets))
            for i, (img, _lab, _score, box) in enumerate(dets):
                cand = [(j, g) for j, g in enumerate(gts) if g[0] == img]
                best, best_iou = None, self.overlap_threshold
                for j, g in cand:
                    iou = self._iou(box, g[2])
                    if iou >= best_iou:
                        best, best_iou = j, iou
                if best is None:
                    fp[i] = 1
                elif gts[best][3] and not self.evaluate_difficult:
                    pass  # difficult boxes neither reward nor punish
                elif best in matched:
                    fp[i] = 1
                else:
                    matched.add(best)
                    tp[i] = 1
            if n_pos == 0:
                continue
            ctp, cfp = np.cumsum(tp), np.cumsum(fp)
            recall = ctp / n_pos
            precision = ctp / np.maximum(ctp + cfp, 1e-12)
            aps.append(self._average_precision(recall, precision))
        return float(np.mean(aps)) if aps else 0.0


class Auc(MetricBase):
    """Streaming ROC AUC from score buckets (reference :Auc)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self._num + 1, np.int64)
        self._stat_neg = np.zeros(self._num + 1, np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim == 2:
            preds = preds[:, -1]
        labels = np.asarray(labels).reshape(-1).astype(bool)
        idx = np.clip((preds * self._num).astype(np.int64), 0, self._num)
        self._stat_pos += np.bincount(idx[labels],
                                      minlength=self._num + 1)
        self._stat_neg += np.bincount(idx[~labels],
                                      minlength=self._num + 1)

    def eval(self):
        # bucket-walk trapezoid integral (same rule as
        # distributed/fleet/util.py auc, without the cross-worker reduce)
        area = 0.0
        tp = fp = 0.0
        pos, neg = self._stat_pos, self._stat_neg
        for i in range(len(pos) - 1, -1, -1):
            new_tp = tp + pos[i]
            new_fp = fp + neg[i]
            area += (new_fp - fp) * (tp + new_tp) / 2.0
            tp, fp = new_tp, new_fp
        if tp == 0 or fp == 0:
            return 0.5
        return float(area / (tp * fp))
