"""Automatic mixed precision.

Reference parity: dygraph AMP (``imperative/amp_auto_cast.cc:27,130`` —
per-op white/black lists casting inputs) + ``paddle.amp.GradScaler``
(``fluid/dygraph/amp/loss_scaler.py:27`` — dynamic loss scaling driven by
``check_finite_and_unscale`` / ``update_loss_scaling`` ops).

TPU-native design: level O1 casts whitelisted-op inputs to **bfloat16**
(the MXU-native dtype) via the dispatcher's amp hook; bf16 needs no loss
scaling, so GradScaler keeps the fp16 API shape but its dynamic-scaling
machinery only activates when dtype='float16' is forced.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Tensor

# reference: fluid/contrib/mixed_precision/fp16_lists.py
WHITE_LIST = {
    "matmul_v2", "matmul", "mul", "conv2d", "conv1d", "conv3d", "linear",
    "lstm_rnn", "gru_rnn", "rnn_rnn", "einsum", "bmm", "addmm",
    "scaled_dot_product_attention", "conv2d_transpose",
}
BLACK_LIST = {
    "exp", "square", "log", "mean", "sum", "cos_sim", "softmax",
    "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
    "cross_entropy", "bce_loss", "layer_norm", "reduce_sum", "reduce_mean",
    "p_norm", "logsumexp", "cumsum",
}

_state = {"enable": False, "dtype": jnp.bfloat16, "level": "O1",
          "custom_white": set(), "custom_black": set()}


def _amp_hook(op_name, arrays):
    if not _state["enable"]:
        return arrays
    white = (WHITE_LIST | _state["custom_white"]) - _state["custom_black"]
    target = _state["dtype"]
    if _state["level"] == "O2":
        if op_name in BLACK_LIST | _state["custom_black"]:
            return [a.astype(jnp.float32)
                    if hasattr(a, "dtype") and a.dtype == target else a
                    for a in arrays]
        return arrays
    if op_name not in white:
        return arrays
    out = []
    for a in arrays:
        if hasattr(a, "dtype") and a.dtype == jnp.float32:
            out.append(a.astype(target))
        else:
            out.append(a)
    return out


dispatch.amp_input_hook = _amp_hook


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """paddle.amp.auto_cast"""
    prev = dict(_state)
    _state["enable"] = enable
    _state["level"] = level
    _state["dtype"] = jnp.bfloat16 if dtype == "bfloat16" else jnp.float16
    _state["custom_white"] = set(custom_white_list or ())
    _state["custom_black"] = set(custom_black_list or ())
    try:
        yield
    finally:
        _state.update(prev)


amp_guard = auto_cast


def is_enabled():
    return _state["enable"]


class GradScaler:
    """paddle.amp.GradScaler (reference: fluid/dygraph/amp/loss_scaler.py:27).

    With bf16 (the TPU default) scaling is an identity pass-through; with
    fp16 the dynamic loss-scale update mirrors update_loss_scaling_op.cc.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        # one fused finite-check across ALL grads, one device->host sync
        # (reference: check_finite_and_unscale_op batches the whole grad
        # list; the per-parameter bool() loop synced once per param)
        flags = []
        for p in optimizer._params():
            if p.grad is not None:
                g = p.grad._data * inv
                flags.append(jnp.any(~jnp.isfinite(g)))
                p.grad._data = g
        self._found_inf = bool(
            jnp.any(jnp.stack(flags))) if flags else False

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._update()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)

    def update(self):
        pass  # paddle's GradScaler.update is folded into step()

    def _update(self):
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate — O2 casts model params to the compute dtype."""
    if level == "O2":
        target = "bfloat16" if dtype == "bfloat16" else "float16"
        if isinstance(models, (list, tuple)):
            for m in models:
                m.to(dtype=target)
        else:
            models.to(dtype=target)
    if optimizers is None:
        return models
    return models, optimizers
