"""Parallel execution engine: sharded train steps + pipeline schedule.

This package is the TPU-native replacement for the reference's
ParallelExecutor/SSA-graph runtime (see train_step.py docstring for the
full mapping).
"""
from .train_step import TrainStep  # noqa: F401
from . import pipeline  # noqa: F401
