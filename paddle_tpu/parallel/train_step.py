"""Sharded, compiled training steps.

Reference parity: this single builder replaces the reference's execution
stack — ParallelExecutor + SSA graph executors
(``parallel_executor.cc:609``, ``fast_threaded_ssa_graph_executor.cc:59``),
the dygraph DDP Reducer (``reducer.cc:270``), the fleet meta-optimizer
program rewrites (sharding/amp/recompute/gradient-merge), and the fused
optimizer passes.  One pjit'd function computes forward, backward, gradient
reduction (implicit via shardings), and the optimizer update; XLA schedules
compute/collective overlap that the reference hand-built with op handles
and comm streams.

Strategy mapping (DistributedStrategy -> jax):
  dp/sharding axes  -> batch PartitionSpec(('dp','sharding'))
  sharding stage 2  -> optimizer-state specs sharded, params replicated
  sharding stage 3  -> parameter specs sharded (ZeRO-3 / FSDP)
  mp                -> explicit per-param specs from TP layers
  pp                -> stacked-block pipeline (parallel/pipeline.py)
  amp               -> bf16 autocast inside the traced step
  gradient_merge    -> lax.scan micro-batch accumulation
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P, NamedSharding

from ..core.tensor import Tensor
from ..core import autograd, rng as rng_mod
from ..jit import functional_call
from ..distributed import mesh as mesh_mod
from ..distributed.sharding import shard_params_specs
from .. import amp as amp_mod

DATA_AXES = mesh_mod.DATA_AXES  # single source: distributed/mesh.py


def _batch_spec(ndim):
    return P(DATA_AXES, *([None] * (ndim - 1)))


def _state_spec_like(param_spec, leaf):
    """Optimizer-state leaf adopts its param's spec when shapes match."""
    if leaf.ndim == 0:
        return P()
    return param_spec


class TrainStep:
    """Compiled train step over a Layer + Optimizer (+ loss)."""

    def __init__(self, model, optimizer, loss_fn=None, strategy=None,
                 mesh=None, amp_level=None, donate=True, train=True,
                 metrics=None):
        from ..distributed.parallel import DataParallel
        from ..distributed.fleet.meta_parallel import PipelineLayer
        if isinstance(model, DataParallel):
            model = model._layers
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.strategy = strategy
        self.mesh = mesh or mesh_mod.ensure_mesh()
        self.donate = donate
        self.training = train
        # metrics computed INSIDE the compiled step (reference:
        # hapi/model.py:1495 threads prepared metrics through train);
        # each step stashes the per-batch metric inputs (e.g. Accuracy's
        # correct matrix) in self.last_metric_outs
        self.metrics = list(metrics or [])
        self.last_metric_outs = []
        self._compiled = {}

        s = strategy
        self.use_amp = bool(amp_level) or bool(s and s.amp)
        self.amp_level = amp_level or (
            "O2" if (s and s.amp_configs.get("use_pure_fp16")) else "O1")
        self.grad_merge_k = 1
        if s and s.gradient_merge:
            self.grad_merge_k = int(
                s.gradient_merge_configs.get("k_steps", 1))

        # metric handles resolved once — step() is the hot path
        from .. import monitor
        self._m_steps = monitor.counter("train.steps",
                                        "TrainStep.step calls")
        self._m_step_time = monitor.histogram(
            "train.step_time_ms",
            "host-side dispatch time per train step (ms)")

        self.is_pipeline = isinstance(model, PipelineLayer) and \
            self.mesh.shape.get("pp", 1) > 1
        if self.is_pipeline:
            self._init_pipeline_state()
        else:
            self._init_flat_state()

    # ------------------------------------------------------------------
    def _stage(self):
        s = self.strategy
        if s is not None and s.sharding:
            return int(s.sharding_configs.get("stage", 2))
        return 0

    def _init_flat_state(self):
        params = dict(self.model.named_parameters())
        buffers = {k: v for k, v in self.model.named_buffers()
                   if v is not None}
        self.pnames = sorted(params)
        self.bnames = sorted(buffers)
        stage = self._stage()
        min_size = 1024
        if self.strategy is not None:
            min_size = int(self.strategy.sharding_configs.get(
                "min_shard_size", 1024))
        spec_map = shard_params_specs(
            self.model, stage=stage if stage else 2,
            axis="sharding", min_size=min_size)
        if stage < 3:
            # stages 0-2: params replicated unless TP says otherwise
            for k in self.pnames:
                if getattr(params[k], "partition_spec", None) is None:
                    spec_map[k] = P()
        self.param_specs = {k: spec_map.get(k, P()) for k in self.pnames}

        # the flat-slab fused optimizer update concatenates params into
        # one vector — only sound when params are REPLICATED (dp).
        # Under TP/FSDP shardings the concat would force all-gathers of
        # every shard each step; keep the per-param path there (those
        # updates are already shard-local).  Passed per-call (no
        # mutation of the caller's optimizer)
        self._fuse_opt = None  # optimizer's own setting
        if any(spec != P() for spec in self.param_specs.values()):
            # unconditional (not gated on the optimizer's CURRENT
            # fuse_update): flipping opt.fuse_update=True after
            # construction must not re-enable the slab path for
            # sharded params
            if getattr(self.optimizer, "fuse_update", False):
                import logging
                logging.getLogger("paddle_tpu").info(
                    "fuse_update disabled for this TrainStep: params are "
                    "sharded (TP/FSDP); the fused flat-slab update applies "
                    "to replicated-param regimes only")
            self._fuse_opt = False

        self.params = {}
        for k in self.pnames:
            arr = params[k]._data
            self.params[k] = jax.device_put(
                arr, NamedSharding(self.mesh, self.param_specs[k]))
        self.buffers = {k: jax.device_put(
            buffers[k]._data, NamedSharding(self.mesh, P()))
            for k in self.bnames}

        self.opt_state = {k: self.optimizer._init_state(params[k])
                          for k in self.pnames}
        # ZeRO stage >= 1: shard optimizer moments over 'sharding'
        self.opt_specs = {}
        shard_world = self.mesh.shape.get("sharding", 1)
        for k in self.pnames:
            pspec = self.param_specs[k]
            sub = {}
            for sk, leaf in self.opt_state[k].items():
                if leaf.ndim == 0:
                    sub[sk] = P()
                elif stage >= 1 and shard_world > 1 and \
                        pspec == P() and leaf.shape and \
                        leaf.shape[0] % shard_world == 0:
                    sub[sk] = P("sharding")
                else:
                    sub[sk] = _state_spec_like(pspec, leaf)
            self.opt_specs[k] = sub
        self.opt_state = {
            k: {sk: jax.device_put(leaf, NamedSharding(
                self.mesh, self.opt_specs[k][sk]))
                for sk, leaf in sub.items()}
            for k, sub in self.opt_state.items()}
        self._trainable = {k: params[k].trainable for k in self.pnames}

    def _init_pipeline_state(self):
        from .pipeline import (stack_block_params, stack_block_buffers,
                               build_pipeline_fn, build_pipeline_1f1b_fn)
        model = self.model
        pp = self.mesh.shape.get("pp", 1)
        nblocks = len(model.blocks)
        assert nblocks % pp == 0, \
            f"n_blocks {nblocks} must divide pp degree {pp}"
        self.bps = nblocks // pp
        self.block_pnames, stacked = stack_block_params(model.blocks)
        self.block_bnames, stacked_bufs = stack_block_buffers(model.blocks)
        # regroup [nblocks, ...] -> [pp, bps, ...]
        self.block_params = {
            k: jax.device_put(
                v.reshape((pp, self.bps) + v.shape[1:]),
                NamedSharding(self.mesh, P("pp")))
            for k, v in stacked.items()}
        self.block_buffers = {
            k: jax.device_put(
                v.reshape((pp, self.bps) + v.shape[1:]),
                NamedSharding(self.mesh, P("pp")))
            for k, v in stacked_bufs.items()}
        self.pre_params = {}
        self.post_params = {}
        if model.pre is not None:
            self.pre_params = {k: jax.device_put(
                p._data, NamedSharding(
                    self.mesh, getattr(p, "partition_spec", None) or P()))
                for k, p in dict(model.pre.named_parameters()).items()}
        if model.post is not None:
            self.post_params = {k: jax.device_put(
                p._data, NamedSharding(
                    self.mesh, getattr(p, "partition_spec", None) or P()))
                for k, p in dict(model.post.named_parameters()).items()}
        M = 1
        schedule = "F-then-B"
        if self.strategy is not None and self.strategy.pipeline:
            M = int(self.strategy.pipeline_configs.get(
                "accumulate_steps", 1))
            schedule = str(self.strategy.pipeline_configs.get(
                "schedule_mode",
                self.strategy.pipeline_configs.get("schedule",
                                                   "F-then-B")))
        self.num_microbatches = max(M, 1)
        self.pipe_schedule = "1F1B" if schedule.upper() == "1F1B" \
            else "F-then-B"
        use_remat = bool(self.strategy and self.strategy.recompute)
        if self.pipe_schedule == "1F1B":
            self.pipe_1f1b, _, _ = build_pipeline_1f1b_fn(
                model, self.num_microbatches, self.loss_fn,
                mesh=self.mesh, training=self.training)
            self.pipe_fn = None
        else:
            self.pipe_fn, _, _ = build_pipeline_fn(
                model, self.num_microbatches, mesh=self.mesh,
                training=self.training, use_recompute=use_remat)
            self.pipe_1f1b = None
        # one flat param tree for the optimizer
        self.params = {"pre": self.pre_params, "block": self.block_params,
                       "post": self.post_params}
        self.opt_state = jax.tree_util.tree_map(
            lambda a: self.optimizer._init_state(Tensor(a)), self.params,
            is_leaf=lambda x: isinstance(x, jax.Array))
        self.buffers = {}
        self.bnames = []

    # ------------------------------------------------------------------
    def _loss_from_out(self, out, labels):
        with autograd.no_grad():
            if self.loss_fn is None:
                loss_t = out if isinstance(out, Tensor) else Tensor(out)
            else:
                wrapped_out = Tensor(out) if not isinstance(out, Tensor) \
                    else out
                wrapped_labels = [Tensor(l) for l in labels]
                loss_t = self.loss_fn(wrapped_out, *wrapped_labels)
            return loss_t._data if isinstance(loss_t, Tensor) else loss_t

    def _build_flat(self, in_shapes):
        model = self.model
        pnames, bnames = self.pnames, self.bnames
        training = self.training
        use_amp, amp_level = self.use_amp, self.amp_level
        merge_k = self.grad_merge_k

        metrics = self.metrics

        def forward_loss(p_arrays, b_arrays, inputs, labels, key):
            import contextlib
            ctx = amp_mod.auto_cast(
                enable=True, level=amp_level) if use_amp else \
                contextlib.nullcontext()
            with ctx:
                with autograd.no_grad():
                    out, new_buf = functional_call(
                        model, dict(zip(pnames, p_arrays)),
                        dict(zip(bnames, b_arrays)), inputs,
                        training=training, rng_key=key)
                if isinstance(out, tuple):
                    out = out[0]
                loss = self._loss_from_out(out, labels)
                # expert-parallel models: add MoE load-balancing aux loss
                # (already scaled by each layer's aux_weight)
                from ..distributed.moe import collect_moe_aux_loss
                aux = collect_moe_aux_loss(model)
                if aux is not None:
                    # loss is a raw array here (see _loss_from_out)
                    loss = loss + (aux._data if isinstance(aux, Tensor)
                                   else aux)
                metric_outs = []
                if metrics:
                    with autograd.no_grad():
                        out_t = out if isinstance(out, Tensor) \
                            else Tensor(out)
                        lab_t = [Tensor(l) for l in labels]
                        for m in metrics:
                            mo = m.compute(out_t, *lab_t)
                            mo = mo if isinstance(mo, (list, tuple)) \
                                else [mo]
                            metric_outs.append(
                                [x._data if isinstance(x, Tensor) else x
                                 for x in mo])
            return loss.astype(jnp.float32), (
                [new_buf[k] for k in bnames], metric_outs)

        trainable = self._trainable

        def step(params, buffers, opt_state, lr, key, inputs, labels):
            p_list = [params[k] for k in pnames]
            b_list = [buffers[k] for k in bnames]

            def loss_of(p_sub):
                merged = [p_sub[k] if trainable[k] else params[k]
                          for k in pnames]
                return forward_loss(merged, b_list, inputs, labels, key)

            p_sub = {k: params[k] for k in pnames if trainable[k]}
            if merge_k > 1:
                def micro(i, acc):
                    g_acc, l_acc, buf = acc
                    mb_in = [a.reshape((merge_k, -1) + a.shape[1:])[i]
                             for a in inputs]
                    mb_lab = [a.reshape((merge_k, -1) + a.shape[1:])[i]
                              for a in labels]

                    def loss_mb(p_sub2):
                        merged = [p_sub2[k] if trainable[k] else params[k]
                                  for k in pnames]
                        return forward_loss(merged, b_list, mb_in, mb_lab,
                                            jax.random.fold_in(key, i))

                    (l, (buf2, mo)), g = jax.value_and_grad(
                        loss_mb, has_aux=True)(p_sub)
                    g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
                    return (g_acc, l_acc + l, buf2), mo

                # unrolled python loop (merge_k is small & static)
                zero_g = jax.tree_util.tree_map(jnp.zeros_like, p_sub)
                g_acc, l_acc, buf = zero_g, jnp.zeros([], jnp.float32), \
                    b_list
                metric_parts = []
                for i in range(merge_k):
                    (g_acc, l_acc, buf), mo = micro(
                        i, (g_acc, l_acc, buf))
                    metric_parts.append(mo)
                grads = jax.tree_util.tree_map(
                    lambda g: g / merge_k, g_acc)
                loss = l_acc / merge_k
                new_b_list = buf
                # combine per-micro metric inputs: batch-dim concat for
                # arrays, stack for scalars (all microbatches reach
                # m.update(); taking only the last would drop 1-1/k of
                # the batch)
                metric_outs = []
                if metric_parts and metric_parts[0]:
                    for mi in range(len(metric_parts[0])):
                        metric_outs.append([
                            jnp.concatenate(
                                [mp[mi][j] for mp in metric_parts])
                            if metric_parts[0][mi][j].ndim else
                            jnp.stack([mp[mi][j] for mp in metric_parts])
                            for j in range(len(metric_parts[0][mi]))])
            else:
                (loss, (new_b_list, metric_outs)), grads = \
                    jax.value_and_grad(loss_of, has_aux=True)(p_sub)

            new_sub, new_opt_sub = self.optimizer.apply_gradients_tree(
                p_sub, grads,
                {k: opt_state[k] for k in p_sub}, lr,
                fuse=self._fuse_opt)
            new_params = dict(params)
            new_params.update(new_sub)
            new_opt = dict(opt_state)
            new_opt.update(new_opt_sub)
            # re-pin shardings so XLA keeps the layout stable
            new_params = {
                k: jax.lax.with_sharding_constraint(
                    v, NamedSharding(self.mesh, self.param_specs[k]))
                for k, v in new_params.items()}
            new_buffers = dict(zip(bnames, new_b_list))
            return loss, new_params, new_buffers, new_opt, metric_outs

        batch_sharding = self._data_sharding

        in_shardings = (
            {k: NamedSharding(self.mesh, self.param_specs[k])
             for k in pnames},
            {k: NamedSharding(self.mesh, P()) for k in bnames},
            {k: {sk: NamedSharding(self.mesh, self.opt_specs[k][sk])
                 for sk in self.opt_specs[k]} for k in pnames},
            NamedSharding(self.mesh, P()),
            NamedSharding(self.mesh, P()),
            [batch_sharding(s) for s in in_shapes[1]],
            [batch_sharding(s) for s in in_shapes[2]],
        )
        donate = (0, 2) if self.donate else ()
        return jax.jit(step, in_shardings=in_shardings,
                       donate_argnums=donate)

    def _build_pipeline(self, in_shapes):
        if self.pipe_schedule == "1F1B":
            return self._build_pipeline_1f1b(in_shapes)
        pipe_fn = self.pipe_fn

        metrics = self.metrics

        def step(params, buffers, opt_state, lr, key, inputs, labels):
            def loss_of(p):
                out, new_bufs = pipe_fn(p["pre"], p["block"], p["post"],
                                        inputs[0], key,
                                        block_buffers=buffers)
                loss = self._loss_from_out(out, labels).astype(
                    jnp.float32)
                metric_outs = []
                if metrics:
                    with autograd.no_grad():
                        out_t = Tensor(out)
                        lab_t = [Tensor(l) for l in labels]
                        for m in metrics:
                            mo = m.compute(out_t, *lab_t)
                            mo = mo if isinstance(mo, (list, tuple)) \
                                else [mo]
                            metric_outs.append(
                                [x._data if isinstance(x, Tensor) else x
                                 for x in mo])
                return loss, (new_bufs, metric_outs)

            (loss, (new_bufs, metric_outs)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            # pipeline: block params are pp-sharded stacks — the flat
            # concat would all-gather them; never fuse here
            new_params, new_opt = self.optimizer.apply_gradients_tree(
                params, grads, opt_state, lr, fuse=False)
            return loss, new_params, new_bufs, new_opt, metric_outs

        donate = (0, 2) if self.donate else ()
        return jax.jit(step, donate_argnums=donate)

    def _build_pipeline_1f1b(self, in_shapes):
        pipe_1f1b = self.pipe_1f1b

        def step(params, buffers, opt_state, lr, key, inputs, labels):
            if self.loss_fn is not None and len(labels) != 1:
                raise ValueError(
                    "1F1B pipeline expects exactly one labels array "
                    f"(got {len(labels)}); GPipe (schedule_mode="
                    "'F-then-B') supports multi-label losses")
            loss, g_pre, g_block, g_post, new_bufs = pipe_1f1b(
                params["pre"], params["block"], params["post"], buffers,
                inputs[0], labels[0] if labels else None, key)
            grads = {"pre": g_pre, "block": g_block, "post": g_post}
            new_params, new_opt = self.optimizer.apply_gradients_tree(
                params, grads, opt_state, lr, fuse=False)
            return loss, new_params, new_bufs, new_opt, []

        if self.metrics:
            import warnings
            warnings.warn(
                "TrainStep(metrics=...) under the 1F1B schedule: the "
                "model output never materializes (loss is consumed "
                "per-microbatch inside the schedule), so in-graph "
                "metrics are not computed — use GPipe "
                "(schedule_mode='F-then-B') or evaluate() for metrics")
        donate = (0, 2) if self.donate else ()
        return jax.jit(step, donate_argnums=donate)

    # ------------------------------------------------------------------
    def _data_sharding(self, shape):
        # non-divisible batches fall back to replicated (correct, just not
        # data-parallel) — policy lives in mesh.batch_partition_spec
        return NamedSharding(self.mesh,
                             mesh_mod.batch_partition_spec(shape,
                                                           self.mesh))

    def _place_inputs(self, inputs, labels):
        """Normalize + place a global batch exactly as the compiled step
        consumes it (single source for step() and aot_compile)."""
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        def _as_array(x):
            # Tensors/jax arrays stay on device; everything else becomes
            # numpy WITHOUT a device commit (placement happens below)
            if isinstance(x, Tensor):
                return x._data
            if isinstance(x, jax.Array):
                return x
            return np.asarray(x)

        in_arrays = [_as_array(x) for x in inputs]
        lab_arrays = [_as_array(x) for x in labels]
        if self.is_pipeline and jax.process_count() > 1:
            # multi-host pipeline: the pp ring may span hosts, so a dp
            # row-block can live on several processes — every process
            # must feed the identical GLOBAL batch (Megatron semantics:
            # ranks within a dp group read the same data) and each cuts
            # out its addressable shards.  Verify the contract once: a
            # per-host local shard fed here would silently train on
            # inconsistent data.
            if not getattr(self, "_mh_feed_checked", False):
                self._mh_feed_checked = True
                import hashlib
                from jax.experimental import multihost_utils
                digest = hashlib.sha256()
                for a in in_arrays + lab_arrays:
                    digest.update(np.ascontiguousarray(a).tobytes())
                h = np.frombuffer(digest.digest()[:8], np.int64)
                gathered = np.asarray(
                    multihost_utils.process_allgather(h))
                if not (gathered == gathered[0]).all():
                    raise ValueError(
                        "multi-host pipeline: processes fed DIFFERENT "
                        "batches. The pp ring spans hosts, so every "
                        "process must feed the identical GLOBAL batch "
                        "(not its local dp shard) — load the same data "
                        "on all ranks of a dp group")
            in_arrays = [mesh_mod.global_from_replicated(a, self.mesh)
                         for a in in_arrays]
            lab_arrays = [mesh_mod.global_from_replicated(a, self.mesh)
                          for a in lab_arrays]
        if not self.is_pipeline:
            if jax.process_count() > 1:
                # multi-host: each process holds its LOCAL batch shard;
                # assemble the global array (reference: per-trainer data
                # partitions feeding one NCCL job)
                in_arrays = [mesh_mod.host_local_to_global(a, self.mesh)
                             for a in in_arrays]
                lab_arrays = [mesh_mod.host_local_to_global(a, self.mesh)
                              for a in lab_arrays]
            else:
                # batches may arrive committed to one device (DataLoader
                # Tensors); re-place them on the mesh so they match the
                # step's declared in_shardings
                in_arrays = [jax.device_put(a, self._data_sharding(a.shape))
                             for a in in_arrays]
                lab_arrays = [jax.device_put(a,
                                             self._data_sharding(a.shape))
                              for a in lab_arrays]
        return in_arrays, lab_arrays

    def step(self, inputs, labels=()):
        """Run one optimization step on a global batch."""
        import time as _time
        t0 = _time.perf_counter()
        in_arrays, lab_arrays = self._place_inputs(inputs, labels)
        key = rng_mod.next_key()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        shapes_key = (len(in_arrays),
                      tuple(a.ndim for a in in_arrays),
                      tuple(a.ndim for a in lab_arrays),
                      tuple(tuple(a.shape) for a in in_arrays),
                      tuple(tuple(a.shape) for a in lab_arrays))
        if shapes_key not in self._compiled:
            meta = (len(in_arrays), [tuple(a.shape) for a in in_arrays],
                    [tuple(a.shape) for a in lab_arrays])
            if self.is_pipeline:
                self._compiled[shapes_key] = self._build_pipeline(meta)
            else:
                self._compiled[shapes_key] = self._build_flat(meta)
        fn = self._compiled[shapes_key]
        if self.is_pipeline:
            (loss, self.params, self.block_buffers, self.opt_state,
             self.last_metric_outs) = fn(
                self.params, self.block_buffers, self.opt_state, lr, key,
                in_arrays, lab_arrays)
        else:
            (loss, self.params, self.buffers, self.opt_state,
             self.last_metric_outs) = fn(
                self.params, self.buffers, self.opt_state, lr, key,
                in_arrays, lab_arrays)
        self.optimizer._step_count += 1
        # dispatch-side step accounting (monitor registry; the step is
        # async, so the histogram measures host dispatch latency — a
        # compile lands in the first observation's tail bucket)
        self._m_steps.inc()
        self._m_step_time.observe((_time.perf_counter() - t0) * 1e3)
        return Tensor(loss)

    def aot_compile(self, inputs, labels=()):
        """AOT lower + compile the step for these batch shapes WITHOUT
        executing it (jax ahead-of-time API).  Returns
        ``(lowered_seconds, compiled_seconds, compiled)`` — use
        ``compiled.memory_analysis()`` / ``cost_analysis()`` to bound
        HBM and XLA time before committing a real device step.  This is
        the big-model rehearsal path: a killed mid-compile on a remote
        chip can wedge the device (observed with GPT-3 1.3B through the
        dev tunnel), so measure compile on a cheap backend first."""
        import time as _time
        # same placement/global-assembly as step(): the rehearsal must
        # lower the SAME program the real step will compile
        in_arrays, lab_arrays = self._place_inputs(inputs, labels)
        meta = (len(in_arrays), [tuple(a.shape) for a in in_arrays],
                [tuple(a.shape) for a in lab_arrays])
        fn = (self._build_pipeline(meta) if self.is_pipeline
              else self._build_flat(meta))
        # fixed dummy key: the key only shapes the trace, and advancing
        # the global stream from a compile-only rehearsal would silently
        # change every subsequent step's randomness
        key = jax.random.key(0)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        state = self.block_buffers if self.is_pipeline else self.buffers
        t0 = _time.perf_counter()
        lowered = fn.lower(self.params, state, self.opt_state, lr, key,
                           in_arrays, lab_arrays)
        t_lower = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        compiled = lowered.compile()
        return t_lower, _time.perf_counter() - t0, compiled

    # ------------------------------------------------------------------
    def sync_to_layer(self):
        """Copy device state back into the Layer's Tensors."""
        if self.is_pipeline:
            from .pipeline import unstack_block_params, \
                unstack_block_buffers
            pp = self.mesh.shape.get("pp", 1)
            flat = {k: np.asarray(v).reshape((-1,) + v.shape[2:])
                    for k, v in self.params["block"].items()}
            unstack_block_params(self.model.blocks, self.block_pnames,
                                 flat)
            flat_b = {k: np.asarray(v).reshape((-1,) + v.shape[2:])
                      for k, v in self.block_buffers.items()}
            unstack_block_buffers(self.model.blocks, self.block_bnames,
                                  flat_b)
            # pre/post params are mesh-committed; re-place on one device
            # so eager eval/predict after training works (same policy as
            # the flat path below)
            dev0 = next(iter(self.mesh.devices.flat))
            for store, params in (("pre", self.params["pre"]),
                                  ("post", self.params["post"])):
                layer = getattr(self.model, store)
                if layer is not None:
                    named = dict(layer.named_parameters())
                    for k, v in params.items():
                        if isinstance(v, jax.Array) and \
                                len(v.devices()) > 1:
                            v = jax.device_put(np.asarray(v), dev0)
                        named[k]._data = v
            return
        # re-place on one device: the Layer copy serves eager eval/predict,
        # where mixing mesh-committed and single-device arrays is an error
        dev = next(iter(self.mesh.devices.flat))

        def _local(a):
            if isinstance(a, jax.Array) and len(a.devices()) > 1:
                return jax.device_put(np.asarray(a), dev)
            return a

        named = dict(self.model.named_parameters())
        for k in self.pnames:
            named[k]._data = _local(self.params[k])
        named_b = dict(self.model.named_buffers())
        for k in self.bnames:
            if k in named_b and named_b[k] is not None:
                named_b[k]._data = _local(self.buffers[k])
