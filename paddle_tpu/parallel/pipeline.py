"""SPMD pipeline-parallel engine: GPipe and 1F1B schedules.

Reference parity: PipelineTrainer + SectionWorker
(``framework/trainer.h:325``, ``section_worker.cc:34`` — synchronous GPipe
F-then-B over micro-batch scopes, stages connected by send_v2/recv_v2).

TPU-native design: no per-stage processes, no send/recv ops.  All identical
stage blocks have their parameters STACKED on a leading 'pp'-sharded axis;
ONE shard_map program runs on every device, rotating activations around the
ring with ``lax.ppermute``.  Two schedules:

- **GPipe** (``build_pipeline_fn``): M + P - 1 forward ticks, backward via
  ``jax.grad`` through the rotation (ppermute's transpose is the reverse
  rotation).  Live state O(M) ticks of residuals (O(M) INPUTS with
  per-tick remat).
- **1F1B** (``build_pipeline_1f1b_fn``): hand-scheduled per-tick VJPs.
  Each tick does one masked forward AND one masked backward; cotangents
  rotate on the reverse ring; stage inputs live in a 2P-slot ring buffer,
  so live activations are O(P) — independent of M — at identical math.
  This is the schedule the reference could not express (section_worker is
  F-then-B only) and the VERDICT round-1 item #3.

Buffers (BN running stats) are threaded functionally through both
schedules: forward ticks that process a real microbatch update the
stage's stacked buffer state; backward-pass recomputation reuses, but
does not re-update, the stats.

Heterogeneous ends (embedding / head) run replicated outside the ring.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core import autograd, rng as rng_mod
from ..jit import functional_call
from ..distributed import mesh as mesh_mod

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore


def stack_block_params(blocks):
    """blocks: LayerList of structurally-identical Layers ->
    (pnames, {name: stacked [n_blocks, ...]})."""
    pnames = [n for n, _ in blocks[0].named_parameters()]
    stacked = {}
    for name in pnames:
        per_block = []
        for blk in blocks:
            p = dict(blk.named_parameters())[name]
            per_block.append(p._data)
        stacked[name] = jnp.stack(per_block)
    return pnames, stacked


def unstack_block_params(blocks, pnames, stacked):
    for i, blk in enumerate(blocks):
        params = dict(blk.named_parameters())
        for name in pnames:
            params[name]._data = stacked[name][i]


def stack_block_buffers(blocks):
    """Like stack_block_params but for buffers (BN running stats)."""
    bnames = [n for n, b in blocks[0].named_buffers() if b is not None]
    stacked = {}
    for name in bnames:
        stacked[name] = jnp.stack(
            [dict(blk.named_buffers())[name]._data for blk in blocks])
    return bnames, stacked


def unstack_block_buffers(blocks, bnames, stacked):
    for i, blk in enumerate(blocks):
        bufs = dict(blk.named_buffers())
        for name in bnames:
            if bufs.get(name) is not None:
                bufs[name]._data = stacked[name][i]


def _run_stage(template_block, pnames, bnames, stage_params, stage_bufs,
               x, training):
    """Run this device's `bps` consecutive blocks: scan over the block
    axis.  stage_params/stage_bufs leaves: [bps, ...].  Returns
    (h, new_stage_bufs)."""

    n_p = len(pnames)

    def one_block(h, leaves):
        params = dict(zip(pnames, leaves[:n_p]))
        bufs = dict(zip(bnames, leaves[n_p:]))
        out, new_buf = functional_call(template_block, params, bufs, (h,),
                                       training=training)
        return out, [new_buf[k] for k in bnames]

    leaves = [stage_params[n] for n in pnames] + \
        [stage_bufs[n] for n in bnames]
    h, new_buf_stacked = lax.scan(one_block, x, leaves)
    return h, dict(zip(bnames, new_buf_stacked))


def _tree_where(pred, new, old):
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(pred, a, b), new, old)


# ===========================================================================
# GPipe (F-then-B) — backward via jax.grad through the rotation
# ===========================================================================

def build_pipeline_fn(pipe_layer, num_microbatches, mesh=None,
                      training=True, axis="pp", use_recompute=False):
    """Returns (forward, pnames, bnames) where
    ``forward(pre_params, block_stacked, post_params, x_global, key,
    block_buffers) -> (out, new_block_buffers)``.

    block_stacked/block_buffers leaves are [pp, bps, ...] (grouped per
    stage).  x_global: [M * mb, ...] global batch.
    """
    mesh = mesh or mesh_mod.ensure_mesh()
    pp = mesh.shape.get(axis, 1)
    template = pipe_layer.blocks[0]
    pnames = [n for n, _ in template.named_parameters()]
    bnames = [n for n, b in template.named_buffers() if b is not None]
    M = num_microbatches
    run_stage = _run_stage
    if use_recompute:
        # remat each pipeline tick: backward recomputes the stage forward
        # instead of storing M+P-1 ticks of activations (the GPipe memory
        # fix the reference gets from RecomputeOptimizer stacking)
        def run_stage(template, pnames, bnames, stage_params, stage_bufs,
                      x, training):
            fn = jax.checkpoint(
                lambda sp, sb, xx: _run_stage(template, pnames, bnames,
                                              sp, sb, xx, training))
            return fn(stage_params, stage_bufs, x)

    def pipeline_core(stage_params, stage_bufs, h_mbs):
        """Inside shard_map: stage_params leaves [bps, ...] (this stage's
        blocks); h_mbs [M, mb, ...] replicated activations after `pre`."""
        stage = lax.axis_index(axis)
        n = lax.axis_size(axis)
        steps = M + n - 1
        mb_shape = h_mbs.shape[1:]
        out_buf = jnp.zeros((M,) + mb_shape, h_mbs.dtype)
        carry = jnp.zeros(mb_shape, h_mbs.dtype)
        perm = [(i, (i + 1) % n) for i in range(n)]

        def tick(t, state):
            carry, out_buf, bufs = state
            feed_idx = jnp.clip(t, 0, M - 1)
            feed = lax.dynamic_index_in_dim(h_mbs, feed_idx, axis=0,
                                            keepdims=False)
            inp = jnp.where(stage == 0, feed, carry)
            act, new_bufs = run_stage(template, pnames, bnames,
                                      stage_params, bufs, inp, training)
            # running stats advance only on ticks where this stage holds
            # a REAL microbatch (reference: per-microbatch scope BN)
            active = jnp.logical_and(t - stage >= 0, t - stage < M)
            bufs = _tree_where(jnp.logical_and(active, training),
                               new_bufs, bufs)
            # collect at the LAST stage for ticks t in [n-1, n-1+M)
            write_idx = jnp.clip(t - (n - 1), 0, M - 1)
            updated = lax.dynamic_update_index_in_dim(
                out_buf, act, write_idx, axis=0)
            collect = jnp.logical_and(stage == n - 1, t >= n - 1)
            out_buf = jnp.where(collect, updated, out_buf)
            carry_next = lax.ppermute(act, axis, perm)
            return carry_next, out_buf, bufs

        carry, out_buf, stage_bufs = lax.fori_loop(
            0, steps, tick, (carry, out_buf, stage_bufs))
        # only the last stage holds data; psum over the ring replicates it
        # (other stages contribute zeros) so out_specs=P() is truthful
        return lax.psum(out_buf, axis), stage_bufs

    def pipelined(block_stacked, block_buffers, h_mbs):
        in_specs = (
            jax.tree_util.tree_map(lambda _: P(axis), block_stacked),
            jax.tree_util.tree_map(lambda _: P(axis), block_buffers),
            P(),
        )

        def core_wrap(bs_local, bb_local, h):
            # shard_map hands local views [1, bps, ...]; drop the pp axis
            bs_local = {k: v[0] for k, v in bs_local.items()}
            bb_local = {k: v[0] for k, v in bb_local.items()}
            out, new_bufs = pipeline_core(bs_local, bb_local, h)
            new_bufs = {k: v[None] for k, v in new_bufs.items()}
            return out, new_bufs

        fn = shard_map(
            core_wrap, mesh=mesh,
            in_specs=in_specs,
            out_specs=(P(), jax.tree_util.tree_map(
                lambda _: P(axis), block_buffers)),
            check_vma=False)
        return fn(block_stacked, block_buffers, h_mbs)

    def forward(pre_params, block_stacked, post_params, x_global, key,
                block_buffers=None, pre_buffers=None, post_buffers=None):
        """Pure pipeline forward over the global batch."""
        pre_buffers = pre_buffers or {}
        post_buffers = post_buffers or {}
        block_buffers = block_buffers if block_buffers is not None else {}
        mb = x_global.shape[0] // M
        rng_mod.push_trace_key(key)
        try:
            with autograd.no_grad():
                if pipe_layer.pre is not None:
                    h, _ = functional_call(pipe_layer.pre, pre_params,
                                           pre_buffers, (x_global,),
                                           training=training)
                else:
                    h = x_global
                h_mbs = h.reshape((M, mb) + h.shape[1:])
                out_mbs, new_block_buffers = pipelined(
                    block_stacked, block_buffers, h_mbs)
                out = out_mbs.reshape((M * mb,) + out_mbs.shape[2:])
                if pipe_layer.post is not None:
                    out, _ = functional_call(pipe_layer.post, post_params,
                                             post_buffers, (out,),
                                             training=training)
        finally:
            rng_mod.pop_trace_key()
        return out, new_block_buffers

    return forward, pnames, bnames


# ===========================================================================
# 1F1B — hand-scheduled per-tick VJPs, live activations O(P) not O(M)
# ===========================================================================

def build_pipeline_1f1b_fn(pipe_layer, num_microbatches, loss_fn,
                           mesh=None, training=True, axis="pp"):
    """Returns (step, pnames, bnames) where ``step(pre_params,
    block_stacked, post_params, block_buffers, x_global, labels, key)
    -> (loss, g_pre, g_block, g_post, new_block_buffers)``.

    Loss aggregation: per-microbatch losses are averaged (sum / M),
    which equals GPipe's full-batch loss for MEAN-reduced criteria (the
    framework's standard losses).  A reduction='sum' criterion differs
    by a factor of M between schedules — use GPipe for sum-reduced
    losses.

    Schedule (synchronous lockstep; one ppermute forward + one reverse
    per tick): stage ``s`` runs the FORWARD of microbatch ``m`` at tick
    ``t = m + s`` and the BACKWARD of ``m`` at ``t = (2P - 1 - s) + m``;
    the last stage's backward seeds from the per-microbatch head+loss
    VJP one tick after its forward.  In-flight inputs per stage are
    bounded by ``2(P - s) - 1 <= 2P - 1``, stored in a 2P-slot ring
    buffer — live state is O(P) instead of GPipe's O(M).  Backward
    recomputes the stage forward from the stored INPUT inside its VJP
    (per-tick rematerialization), so residuals never accumulate.
    """
    mesh = mesh or mesh_mod.ensure_mesh()
    pp = int(mesh.shape.get(axis, 1))
    template = pipe_layer.blocks[0]
    pnames = [n for n, _ in template.named_parameters()]
    bnames = [n for n, b in template.named_buffers() if b is not None]
    M = int(num_microbatches)
    B = 2 * pp  # input ring-buffer slots; in-flight < 2P proves safety
    T = M + 2 * pp - 2 + 1  # last backward: stage 0, m=M-1 at 2P-2+M-1

    def stage_fwd(sp, sb, x):
        return _run_stage(template, pnames, bnames, sp, sb, x, training)

    _buf_check_done = []

    def _check_recompute_buffer_safety(sp, sb, x_example):
        """The backward recompute replays the stage forward against
        step-start buffers while the forward sub-tick used
        per-microbatch-advanced ones.  Sound ONLY when the training
        forward's ACTIVATION never reads buffer values (it may still
        WRITE running stats — BN does exactly that, normalizing with
        batch stats).  Verified mechanically once per build: DCE the
        stage jaxpr keeping just the activation output and confirm no
        buffer input survives."""
        if _buf_check_done or not bnames or not training:
            return
        from jax.interpreters import partial_eval as pe
        jaxpr = jax.make_jaxpr(
            lambda p, b, x: stage_fwd(p, b, x)[0])(sp, sb, x_example)
        n_p = len(jax.tree_util.tree_leaves(sp))
        n_b = len(jax.tree_util.tree_leaves(sb))
        _, used = pe.dce_jaxpr(jaxpr.jaxpr,
                               [True] * len(jaxpr.jaxpr.outvars))
        buf_used = used[n_p:n_p + n_b]
        if any(buf_used):
            # dict pytrees flatten in sorted-key order
            names = [n for n, u in zip(sorted(sb), buf_used) if u]
            raise NotImplementedError(
                "1F1B: this stage's TRAINING forward reads buffer "
                f"values ({names}); the per-tick recompute would replay "
                "it against step-start buffers and silently diverge "
                "from the actual forward.  Use the GPipe schedule "
                "(which stores no stale snapshots) for buffer-READING "
                "training forwards.")
        # marked done only AFTER passing — a caught-and-retried failing
        # first step must re-run the guard, not skip into unsound math
        _buf_check_done.append(True)

    def head_loss(post_params, out_mb, label_mb):
        with autograd.no_grad():
            if pipe_layer.post is not None:
                out_mb, _ = functional_call(
                    pipe_layer.post, post_params, {}, (out_mb,),
                    training=training)
            from ..core.tensor import Tensor
            if loss_fn is None:
                loss_t = out_mb
            else:
                loss_t = loss_fn(Tensor(out_mb), Tensor(label_mb))
                loss_t = loss_t._data if isinstance(loss_t, Tensor) \
                    else loss_t
        return jnp.asarray(loss_t, jnp.float32)

    def core(stage_params, stage_bufs, post_params, h_mbs, labels_mbs,
             key):
        _check_recompute_buffer_safety(stage_params, stage_bufs, h_mbs[0])
        stage = lax.axis_index(axis)
        n = pp
        mb_shape = h_mbs.shape[1:]
        perm_fwd = [(i, (i + 1) % n) for i in range(n)]
        perm_bwd = [(i, (i - 1) % n) for i in range(n)]
        is_last = stage == n - 1

        def composed(sp, post_p, inp, label, k):
            """loss-and-activation of this stage; the single VJP target.
            Seeding (1, 0) gives the last stage's head+loss backward;
            seeding (0, cot) gives an interior stage's backward."""
            rng_mod.push_trace_key(k)
            try:
                with autograd.no_grad():
                    out, _ = stage_fwd(sp, stage_bufs_frozen, inp)
                    loss = head_loss(post_p, out, label)
            finally:
                rng_mod.pop_trace_key()
            return loss, out

        # buffers are advanced on forward ticks only; the VJP recompute
        # reads a frozen copy (no double-update of running stats)
        stage_bufs_frozen = stage_bufs

        state = dict(
            act_carry=jnp.zeros(mb_shape, h_mbs.dtype),
            cot_carry=jnp.zeros(mb_shape, h_mbs.dtype),
            in_buf=jnp.zeros((B,) + mb_shape, h_mbs.dtype),
            dh_buf=jnp.zeros((M,) + mb_shape, h_mbs.dtype),
            g_stage={k: jnp.zeros_like(v) for k, v in
                     stage_params.items()},
            g_post=jax.tree_util.tree_map(jnp.zeros_like, post_params),
            loss_acc=jnp.zeros((), jnp.float32),
            bufs=stage_bufs,
        )

        def tick(t, st):
            # ---- forward sub-tick: stage s, microbatch f_m = t - s ----
            f_m = t - stage
            f_active = jnp.logical_and(f_m >= 0, f_m < M)
            feed = lax.dynamic_index_in_dim(
                h_mbs, jnp.clip(f_m, 0, M - 1), axis=0, keepdims=False)
            inp = jnp.where(stage == 0, feed, st["act_carry"])
            k_f = jax.random.fold_in(jax.random.fold_in(key, stage),
                                     jnp.clip(f_m, 0, M - 1))
            rng_mod.push_trace_key(k_f)
            try:
                with autograd.no_grad():
                    act, new_bufs = stage_fwd(stage_params, st["bufs"],
                                              inp)
            finally:
                rng_mod.pop_trace_key()
            bufs = _tree_where(jnp.logical_and(f_active, training),
                               new_bufs, st["bufs"])
            in_buf = jnp.where(
                f_active,
                lax.dynamic_update_index_in_dim(
                    st["in_buf"], inp, jnp.clip(f_m, 0, M - 1) % B,
                    axis=0),
                st["in_buf"])
            act_send = jnp.where(f_active, act,
                                 jnp.zeros_like(act))
            act_carry = lax.ppermute(act_send, axis, perm_fwd)

            # ---- backward sub-tick: microbatch b_m = t - (2n-1-s) -----
            b_m = t - (2 * n - 1 - stage)
            b_active = jnp.logical_and(b_m >= 0, b_m < M)
            b_idx = jnp.clip(b_m, 0, M - 1)
            stored_inp = lax.dynamic_index_in_dim(
                in_buf, b_idx % B, axis=0, keepdims=False)
            label_mb = lax.dynamic_index_in_dim(
                labels_mbs, b_idx, axis=0, keepdims=False)
            k_b = jax.random.fold_in(jax.random.fold_in(key, stage),
                                     b_idx)
            (loss_m, _), vjp_fn = jax.vjp(
                lambda sp, pp_, i: composed(sp, pp_, i, label_mb, k_b),
                stage_params, post_params, stored_inp)
            seed_loss = jnp.where(is_last, 1.0, 0.0).astype(jnp.float32)
            seed_act = jnp.where(is_last,
                                 jnp.zeros(mb_shape, act.dtype),
                                 st["cot_carry"])
            g_sp, g_pp, g_inp = vjp_fn((seed_loss, seed_act))
            g_stage = jax.tree_util.tree_map(
                lambda acc, g: acc + jnp.where(b_active, g,
                                               jnp.zeros_like(g)),
                st["g_stage"], g_sp)
            g_post = jax.tree_util.tree_map(
                lambda acc, g: acc + jnp.where(b_active, g,
                                               jnp.zeros_like(g)),
                st["g_post"], g_pp)
            loss_acc = st["loss_acc"] + jnp.where(
                jnp.logical_and(b_active, is_last), loss_m, 0.0)
            # stage 0's input cotangent is d loss_m / d h_mb
            dh_buf = jnp.where(
                jnp.logical_and(b_active, stage == 0),
                lax.dynamic_update_index_in_dim(
                    st["dh_buf"], g_inp, b_idx, axis=0),
                st["dh_buf"])
            cot_send = jnp.where(b_active, g_inp,
                                 jnp.zeros_like(g_inp))
            cot_carry = lax.ppermute(cot_send, axis, perm_bwd)

            return dict(act_carry=act_carry, cot_carry=cot_carry,
                        in_buf=in_buf, dh_buf=dh_buf, g_stage=g_stage,
                        g_post=g_post, loss_acc=loss_acc, bufs=bufs)

        st = lax.fori_loop(0, T, tick, state)
        # last stage holds loss + g_post; stage 0 holds dh; psum merges
        # (inactive stages contributed zeros)
        loss = lax.psum(st["loss_acc"], axis)
        g_post = jax.tree_util.tree_map(
            lambda g: lax.psum(g, axis), st["g_post"])
        dh = lax.psum(st["dh_buf"], axis)
        return loss, dh, st["g_stage"], g_post, st["bufs"]

    def step(pre_params, block_stacked, post_params, block_buffers,
             x_global, labels, key):
        block_buffers = block_buffers if block_buffers is not None else {}
        mb = x_global.shape[0] // M

        def pre_fn(pp_):
            with autograd.no_grad():
                rng_mod.push_trace_key(jax.random.fold_in(key, 10 ** 6))
                try:
                    if pipe_layer.pre is not None:
                        h, _ = functional_call(pipe_layer.pre, pp_, {},
                                               (x_global,),
                                               training=training)
                    else:
                        h = x_global
                finally:
                    rng_mod.pop_trace_key()
            return h

        h, pre_vjp = jax.vjp(pre_fn, pre_params)
        h_mbs = h.reshape((M, mb) + h.shape[1:])
        lab_mbs = labels.reshape((M, mb) + labels.shape[1:]) \
            if labels is not None else jnp.zeros((M, mb), jnp.int32)

        in_specs = (
            jax.tree_util.tree_map(lambda _: P(axis), block_stacked),
            jax.tree_util.tree_map(lambda _: P(axis), block_buffers),
            jax.tree_util.tree_map(lambda _: P(), post_params),
            P(), P(), P(),
        )
        out_specs = (
            P(),                                             # loss
            P(),                                             # dh
            jax.tree_util.tree_map(lambda _: P(axis), block_stacked),
            jax.tree_util.tree_map(lambda _: P(), post_params),
            jax.tree_util.tree_map(lambda _: P(axis), block_buffers),
        )

        def core_wrap(bs_local, bb_local, post_p, h_m, lab_m, k):
            bs_local = {k2: v[0] for k2, v in bs_local.items()}
            bb_local = {k2: v[0] for k2, v in bb_local.items()}
            loss, dh, g_stage, g_post, bufs = core(
                bs_local, bb_local, post_p, h_m, lab_m, k)
            return (loss, dh,
                    {k2: v[None] for k2, v in g_stage.items()},
                    g_post,
                    {k2: v[None] for k2, v in bufs.items()})

        fn = shard_map(core_wrap, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=False)
        loss, dh, g_block, g_post, new_bufs = fn(
            block_stacked, block_buffers, post_params, h_mbs, lab_mbs,
            key)
        dh_flat = dh.reshape((M * mb,) + dh.shape[2:])
        (g_pre,) = pre_vjp(dh_flat.astype(h.dtype))
        scale = 1.0 / M
        loss = loss * scale
        g_pre = jax.tree_util.tree_map(lambda g: g * scale, g_pre)
        g_block = jax.tree_util.tree_map(lambda g: g * scale, g_block)
        g_post = jax.tree_util.tree_map(lambda g: g * scale, g_post)
        return loss, g_pre, g_block, g_post, new_bufs

    return step, pnames, bnames
