"""SPMD pipeline-parallel engine.

Reference parity: PipelineTrainer + SectionWorker
(``framework/trainer.h:325``, ``section_worker.cc:34`` — synchronous GPipe
F-then-B over micro-batch scopes, stages connected by send_v2/recv_v2).

TPU-native design: no per-stage processes, no send/recv ops.  All identical
stage blocks have their parameters STACKED on a leading 'pp'-sharded axis;
ONE shard_map program runs on every device, rotating activations around the
ring with ``lax.ppermute`` for M + P - 1 ticks (the GPipe schedule).
Backward is just ``jax.grad`` through the rotation — ppermute's transpose is
the reverse rotation, which reproduces the reference's backward P2P sends.
Heterogeneous ends (embedding / head) run replicated outside the ring.

On 1F1B: a hand-scheduled 1F1B (one backward interleaved per forward after
warm-up) would cap live activations at P microbatches instead of M, but
requires replacing ``jax.grad`` with explicit per-tick VJPs whose residuals
are threaded through the loop carry.  With ``use_recompute=True`` (per-tick
``jax.checkpoint``, the path TrainStep enables for strategy.recompute) the
stored state is already only the M+P-1 tick INPUTS — within M/P of 1F1B's
footprint at identical FLOPs — so the schedule upgrade buys little on TPU
HBM and is deliberately deferred; this note records the analysis.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..core import autograd, rng as rng_mod
from ..jit import functional_call
from ..distributed import mesh as mesh_mod

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore


def stack_block_params(blocks):
    """blocks: LayerList of structurally-identical Layers ->
    (pnames, {name: stacked [n_blocks, ...]})."""
    pnames = [n for n, _ in blocks[0].named_parameters()]
    stacked = {}
    for name in pnames:
        per_block = []
        for blk in blocks:
            p = dict(blk.named_parameters())[name]
            per_block.append(p._data)
        stacked[name] = jnp.stack(per_block)
    return pnames, stacked


def unstack_block_params(blocks, pnames, stacked):
    for i, blk in enumerate(blocks):
        params = dict(blk.named_parameters())
        for name in pnames:
            params[name]._data = stacked[name][i]


def _run_stage(template_block, pnames, stage_params, x, training):
    """Run this device's `bps` consecutive blocks: scan over the block axis.
    stage_params leaves: [bps, ...]."""

    def one_block(h, block_leaves):
        params = dict(zip(pnames, block_leaves))
        out, _ = functional_call(template_block, params, {}, (h,),
                                 training=training)
        return out, None

    leaves = [stage_params[n] for n in pnames]
    h, _ = lax.scan(one_block, x, leaves)
    return h


def build_pipeline_fn(pipe_layer, num_microbatches, mesh=None,
                      training=True, axis="pp", use_recompute=False):
    """Returns a pure fn(pre_params, block_stacked, post_params, buffers,
    x_global, labels_or_None, key) -> stacked per-microbatch outputs.

    block_stacked leaves are [pp, bps, ...] (already grouped per stage).
    x_global: [M * mb, ...] global batch (M = num_microbatches).
    """
    mesh = mesh or mesh_mod.ensure_mesh()
    pp = mesh.shape.get(axis, 1)
    template = pipe_layer.blocks[0]
    pnames = [n for n, _ in template.named_parameters()]
    M = num_microbatches
    run_stage = _run_stage
    if use_recompute:
        # remat each pipeline tick: backward recomputes the stage forward
        # instead of storing M+P-1 ticks of activations (the GPipe memory
        # fix the reference gets from RecomputeOptimizer stacking)
        def run_stage(template, pnames, stage_params, x, training):
            fn = jax.checkpoint(
                lambda sp, xx: _run_stage(template, pnames, sp, xx,
                                          training))
            return fn(stage_params, x)

    def pipeline_core(stage_params, h_mbs):
        """Inside shard_map: stage_params leaves [bps, ...] (this stage's
        blocks); h_mbs [M, mb, ...] replicated activations after `pre`."""
        stage = lax.axis_index(axis)
        n = lax.axis_size(axis)
        steps = M + n - 1
        mb_shape = h_mbs.shape[1:]
        out_buf = jnp.zeros((M,) + mb_shape, h_mbs.dtype)
        carry = jnp.zeros(mb_shape, h_mbs.dtype)
        perm = [(i, (i + 1) % n) for i in range(n)]

        def tick(t, state):
            carry, out_buf = state
            feed_idx = jnp.clip(t, 0, M - 1)
            feed = lax.dynamic_index_in_dim(h_mbs, feed_idx, axis=0,
                                            keepdims=False)
            inp = jnp.where(stage == 0, feed, carry)
            act = run_stage(template, pnames, stage_params, inp, training)
            # collect at the LAST stage for ticks t in [n-1, n-1+M)
            write_idx = jnp.clip(t - (n - 1), 0, M - 1)
            updated = lax.dynamic_update_index_in_dim(
                out_buf, act, write_idx, axis=0)
            collect = jnp.logical_and(stage == n - 1, t >= n - 1)
            out_buf = jnp.where(collect, updated, out_buf)
            carry_next = lax.ppermute(act, axis, perm)
            return carry_next, out_buf

        carry, out_buf = lax.fori_loop(0, steps, tick, (carry, out_buf))
        # only the last stage holds data; psum over the ring replicates it
        # (other stages contribute zeros) so out_specs=P() is truthful
        return lax.psum(out_buf, axis)

    def pipelined(block_stacked, h_mbs):
        in_specs = (
            jax.tree_util.tree_map(lambda _: P(axis), block_stacked),
            P(),
        )

        def core_wrap(bs_local, h):
            # shard_map hands local views [1, bps, ...]; drop the pp axis
            bs_local = {k: v[0] for k, v in bs_local.items()}
            return pipeline_core(bs_local, h)

        fn = shard_map(core_wrap, mesh=mesh, in_specs=in_specs,
                       out_specs=P(), check_vma=False)
        return fn(block_stacked, h_mbs)

    def forward(pre_params, block_stacked, post_params, x_global, key,
                pre_buffers=None, post_buffers=None):
        """Pure pipeline forward over the global batch."""
        pre_buffers = pre_buffers or {}
        post_buffers = post_buffers or {}
        mb = x_global.shape[0] // M
        rng_mod.push_trace_key(key)
        try:
            with autograd.no_grad():
                if pipe_layer.pre is not None:
                    h, _ = functional_call(pipe_layer.pre, pre_params,
                                           pre_buffers, (x_global,),
                                           training=training)
                else:
                    h = x_global
                h_mbs = h.reshape((M, mb) + h.shape[1:])
                out_mbs = pipelined(block_stacked, h_mbs)
                out = out_mbs.reshape((M * mb,) + out_mbs.shape[2:])
                if pipe_layer.post is not None:
                    out, _ = functional_call(pipe_layer.post, post_params,
                                             post_buffers, (out,),
                                             training=training)
        finally:
            rng_mod.pop_trace_key()
        return out

    return forward, pnames
