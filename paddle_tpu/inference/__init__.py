"""Inference API.

Reference parity: ``paddle.inference`` — AnalysisConfig/Predictor
(``inference/api/analysis_predictor.cc:1129,353``).  TPU-native: the "IR
optimization pipeline" is XLA itself; a Predictor wraps an exported
StableHLO artifact (jit.save output) or a live Layer compiled with jax.jit.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


class Config:
    """AnalysisConfig parity (the optimization knobs are no-ops: XLA decides)."""

    def __init__(self, model_path=None, params_path=None):
        self.model_path = model_path
        self.params_path = params_path
        self._enable_memory_optim = True

    def enable_use_gpu(self, *a, **k):
        pass

    def enable_xpu(self, *a, **k):
        pass

    def disable_gpu(self):
        pass

    def switch_ir_optim(self, flag=True):
        pass

    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = flag

    def enable_tensorrt_engine(self, *a, **k):
        pass  # XLA is the engine on TPU

    def set_cpu_math_library_num_threads(self, n):
        pass


class PredictorTensor:
    """Zero-copy-ish handle mirroring paddle_infer.Tensor."""

    def __init__(self, predictor, name, is_input):
        self._predictor = predictor
        self._name = name
        self._is_input = is_input

    def copy_from_cpu(self, arr):
        self._predictor._inputs[self._name] = np.asarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._predictor._outputs[self._name])

    def shape(self):
        if self._is_input:
            return list(self._predictor._inputs[self._name].shape)
        return list(self._predictor._outputs[self._name].shape)


class Predictor:
    def __init__(self, config_or_layer):
        self._inputs = {}
        self._outputs = {}
        if isinstance(config_or_layer, Config):
            from .. import jit as jit_mod
            base = config_or_layer.model_path
            if base.endswith(".pdmodel"):
                base = base[:-len(".pdmodel")]
            self._layer = jit_mod.load(base)
        else:
            layer = config_or_layer
            layer.eval()
            self._layer = layer
        self._input_names = ["x"]
        self._output_names = ["out"]

    def get_input_names(self):
        return list(self._input_names)

    def get_output_names(self):
        return list(self._output_names)

    def get_input_handle(self, name):
        return PredictorTensor(self, name, True)

    def get_output_handle(self, name):
        return PredictorTensor(self, name, False)

    def run(self, inputs=None):
        if inputs is not None:
            arrays = [np.asarray(a) for a in inputs]
        else:
            arrays = [self._inputs[n] for n in self._input_names]
        out = self._layer(*[Tensor(a) for a in arrays])
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._output_names = [f"out_{i}" if i else "out"
                              for i in range(len(outs))]
        for n, o in zip(self._output_names, outs):
            self._outputs[n] = o.numpy() if isinstance(o, Tensor) else o
        if inputs is not None:
            return [self._outputs[n] for n in self._output_names]
        return True


def create_predictor(config):
    return Predictor(config)


CreatePaddlePredictor = create_predictor
