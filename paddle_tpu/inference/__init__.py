"""Inference API.

Reference parity: ``paddle.inference`` — AnalysisConfig/Predictor
(``inference/api/analysis_predictor.cc:1129,353``, pybind surface
``pybind/inference_api.cc``).  TPU-native: the "IR optimization pipeline"
is XLA itself; a Predictor runs an exported StableHLO artifact (from
``paddle.jit.save`` or ``paddle.static.save_inference_model``) or a live
Layer compiled on first use.  TensorRT/MKLDNN knobs are accepted and
ignored — there is no separate engine to delegate to.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor


class Config:
    """AnalysisConfig parity (optimization knobs are no-ops: XLA decides)."""

    def __init__(self, model_path=None, params_path=None):
        if model_path and model_path.endswith(".pdmodel"):
            model_path = model_path[:-len(".pdmodel")]
        self.model_path = model_path
        self.params_path = params_path
        self._enable_memory_optim = True

    def enable_use_gpu(self, *a, **k):
        pass

    def enable_xpu(self, *a, **k):
        pass

    def disable_gpu(self):
        pass

    def switch_ir_optim(self, flag=True):
        pass

    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = flag

    def enable_tensorrt_engine(self, *a, **k):
        pass  # XLA is the engine on TPU

    def set_cpu_math_library_num_threads(self, n):
        pass


class PredictorTensor:
    """Handle mirroring paddle_infer.Tensor (zero-copy where possible)."""

    def __init__(self, predictor, name, is_input):
        self._predictor = predictor
        self._name = name
        self._is_input = is_input

    def copy_from_cpu(self, arr):
        self._predictor._inputs[self._name] = np.asarray(arr)

    def copy_to_cpu(self):
        return np.asarray(self._predictor._outputs[self._name])

    def shape(self):
        if self._is_input:
            return list(self._predictor._inputs[self._name].shape)
        return list(self._predictor._outputs[self._name].shape)


class Predictor:
    """Runs a saved artifact (static or jit export) or a live Layer."""

    def __init__(self, config_or_layer):
        self._inputs = {}
        self._outputs = {}
        self._static_prog = None
        self._layer = None
        if isinstance(config_or_layer, Config):
            base = config_or_layer.model_path
            meta = None
            if os.path.exists(base + ".pdmeta"):
                with open(base + ".pdmeta", "rb") as f:
                    meta = pickle.load(f)
            if meta and meta.get("kind") == "static_inference":
                from ..static.io import load_inference_model
                prog, feeds, _ = load_inference_model(base)
                self._static_prog = prog
                self._input_names = feeds
                self._output_names = [f"out_{i}" if i else "out"
                                      for i in range(prog.n_fetch)]
            else:
                from .. import jit as jit_mod
                self._layer = jit_mod.load(base)
                feeds = (meta or {}).get("feed_names") or ["x"]
                n_out = (meta or {}).get("n_fetch", 1)
                self._input_names = list(feeds)
                self._output_names = [f"out_{i}" if i else "out"
                                      for i in range(n_out)]
        else:
            layer = config_or_layer
            layer.eval()
            self._layer = layer
            self._input_names = ["x"]
            self._output_names = ["out"]

    def get_input_names(self):
        return list(self._input_names)

    def get_output_names(self):
        return list(self._output_names)

    def get_input_handle(self, name):
        return PredictorTensor(self, name, True)

    def get_output_handle(self, name):
        return PredictorTensor(self, name, False)

    def run(self, inputs=None):
        if inputs is not None:
            arrays = [np.asarray(a) for a in inputs]
        else:
            arrays = [self._inputs[n] for n in self._input_names]
        if self._static_prog is not None:
            outs = self._static_prog.run(dict(zip(self._input_names,
                                                  arrays)))
            outs = [np.asarray(o) for o in outs]
        else:
            out = self._layer(*[Tensor(a) for a in arrays])
            raw = out if isinstance(out, (list, tuple)) else [out]
            outs = [o.numpy() if isinstance(o, Tensor) else np.asarray(o)
                    for o in raw]
        self._output_names = [f"out_{i}" if i else "out"
                              for i in range(len(outs))]
        for n, o in zip(self._output_names, outs):
            self._outputs[n] = o
        if inputs is not None:
            return outs
        return True


def create_predictor(config):
    return Predictor(config)


CreatePaddlePredictor = create_predictor
