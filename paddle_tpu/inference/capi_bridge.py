"""Python side of the C inference API.

``csrc/capi.cc`` embeds CPython and calls these helpers, mirroring the
reference's C binding (``paddle/fluid/inference/capi/pd_predictor.cc``)
over the TPU-native Predictor.  Handles are small ints so the C side never
owns a PyObject* for a predictor; output buffers are returned as ``bytes``
whose lifetime the C side manages by holding the reference until the next
fetch or predictor deletion.
"""
from __future__ import annotations

import os

import numpy as np

_predictors = {}
_next_handle = [1]

# dtype codes shared with csrc/paddle_capi.h (PD_DataType)
_CODE_TO_DTYPE = {
    0: np.float32,
    1: np.int64,
    2: np.int32,
    3: np.uint8,
    4: np.float16,
}
_DTYPE_TO_CODE = {np.dtype(v): k for k, v in _CODE_TO_DTYPE.items()}


def _apply_platform_env():
    # Honour JAX_PLATFORMS even under backend plugins that ignore the env
    # var (the axon TPU plugin) — embedded callers select the platform by
    # exporting JAX_PLATFORMS before the first PD_NewPredictor.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        try:
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass


def new_predictor(model_path, params_path):
    _apply_platform_env()
    from . import Config, Predictor

    cfg = Config(model_path or None, params_path or None)
    handle = _next_handle[0]
    _next_handle[0] += 1
    _predictors[handle] = Predictor(cfg)
    return handle


def delete_predictor(handle):
    _predictors.pop(handle, None)


def input_names(handle):
    return list(_predictors[handle].get_input_names())


def output_names(handle):
    return list(_predictors[handle].get_output_names())


def set_input(handle, name, buf, shape, dtype_code):
    dtype = _CODE_TO_DTYPE[int(dtype_code)]
    arr = np.frombuffer(buf, dtype=dtype).reshape([int(s) for s in shape])
    # copy: the caller's buffer is only valid for the duration of this call
    _predictors[handle].get_input_handle(name).copy_from_cpu(arr.copy())


def run(handle):
    _predictors[handle].run()


def get_output(handle, name):
    arr = _predictors[handle].get_output_handle(name).copy_to_cpu()
    arr = np.ascontiguousarray(arr)
    code = _DTYPE_TO_CODE.get(arr.dtype)
    if code is None:  # e.g. bfloat16 / float64 -> widen to float32
        arr = np.ascontiguousarray(arr.astype(np.float32))
        code = 0
    return arr.tobytes(), [int(s) for s in arr.shape], int(code)
