"""reference: python/paddle/dataset/common.py — cache-home helpers.

No-egress environment: ``download`` NEVER fetches; it returns the local
cache path when the file exists and raises a guided error otherwise
(the class-style datasets' synthetic fallbacks are the offline path)."""
from __future__ import annotations

import hashlib
import os

def data_home():
    """Cache root, resolved at CALL time so PADDLE_TPU_DATA_HOME set
    after import still applies — the single definition every dataset
    module (vision/text/1.x readers) shares."""
    return os.path.expanduser(os.environ.get(
        "PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


DATA_HOME = data_home()

__all__ = ["DATA_HOME", "data_home", "md5file", "download"]


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    dirname = os.path.join(data_home(), module_name)
    filename = os.path.join(
        dirname, save_name or url.split("/")[-1])
    if os.path.exists(filename):
        if not md5sum or md5file(filename) == md5sum:
            return filename
        raise RuntimeError(
            f"paddle.dataset: {filename} exists but its md5 does not "
            f"match {md5sum} — the file is corrupt or truncated; "
            "replace it (this environment cannot re-download)")
    raise RuntimeError(
        f"paddle.dataset: {filename} is not cached and this environment "
        "has no network egress — place the file there manually, or use "
        "the paddle_tpu.vision/text dataset classes, whose synthetic "
        "fallback needs no data")
