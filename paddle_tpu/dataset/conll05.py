"""reference: python/paddle/dataset/conll05.py — SRL tuples."""
from __future__ import annotations

__all__ = ["get_dict", "test"]


def get_dict():
    from ..text.datasets import Conll05st
    return Conll05st().get_dict()


def test():
    def reader():
        from ..text.datasets import Conll05st
        ds = Conll05st()
        for i in range(len(ds)):
            yield ds[i]
    return reader
