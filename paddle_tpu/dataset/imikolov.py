"""reference: python/paddle/dataset/imikolov.py — PTB n-grams."""
from __future__ import annotations

__all__ = ["build_dict", "train", "test"]


def build_dict(min_word_freq=50):
    from ..text.datasets import Imikolov
    ds = Imikolov(mode="train", min_word_freq=min_word_freq)
    return {i: i for i in range(ds.VOCAB)}


def _reader(mode, word_idx, n, data_type):
    def reader():
        from ..text.datasets import Imikolov
        dt = "NGRAM" if str(data_type).upper().startswith("N") or \
            data_type == 1 else "SEQ"
        ds = Imikolov(mode=mode, data_type=dt, window_size=n)
        for i in range(len(ds)):
            yield tuple(int(x) if getattr(x, "ndim", 1) == 0 else x
                        for x in ds[i])
    return reader


def train(word_idx, n, data_type="NGRAM"):
    return _reader("train", word_idx, n, data_type)


def test(word_idx, n, data_type="NGRAM"):
    return _reader("test", word_idx, n, data_type)
