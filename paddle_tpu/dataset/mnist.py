"""reference: python/paddle/dataset/mnist.py — yields
(image[784] float32 in [-1, 1], label int)."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test"]


def _reader(mode):
    def reader():
        from ..vision.datasets import MNIST
        ds = MNIST(mode=mode)
        for i in range(len(ds)):
            img, label = ds[i]
            flat = np.asarray(img, np.float32).reshape(-1)
            # reference scaling: idx bytes / 127.5 - 1; the class dataset
            # already divides by 255, so rescale to [-1, 1]
            yield flat * 2.0 - 1.0, int(label)
    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")


def fetch():
    """Materialize both splits (cache validation / synthetic warm-up) —
    generators are lazy, so actually pull one sample from each."""
    next(iter(train()()))
    next(iter(test()()))
