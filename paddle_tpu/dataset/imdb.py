"""reference: python/paddle/dataset/imdb.py — yields
(word_id list, 0/1 label)."""
from __future__ import annotations

__all__ = ["build_dict", "train", "test", "word_dict"]


def word_dict(cutoff=150):
    from ..text.datasets import Imdb
    return Imdb(mode="train", cutoff=cutoff).word_idx


build_dict = word_dict


def _reader(mode):
    def reader():
        from ..text.datasets import Imdb
        ds = Imdb(mode=mode)
        for i in range(len(ds)):
            doc, label = ds[i]
            yield list(int(w) for w in doc), int(label[0])
    return reader


def train(word_idx=None):
    return _reader("train")


def test(word_idx=None):
    return _reader("test")
