"""reference: python/paddle/dataset/wmt16.py — (src, trg, trg_next)."""
from __future__ import annotations

__all__ = ["train", "test"]


def _reader(mode, src_dict_size, trg_dict_size):
    def reader():
        from ..text.datasets import WMT16
        ds = WMT16(mode=mode, src_dict_size=src_dict_size,
                   trg_dict_size=trg_dict_size)
        for i in range(len(ds)):
            yield ds[i]
    return reader


def train(src_dict_size=10000, trg_dict_size=10000, src_lang="en"):
    return _reader("train", src_dict_size, trg_dict_size)


def test(src_dict_size=10000, trg_dict_size=10000, src_lang="en"):
    return _reader("test", src_dict_size, trg_dict_size)
