"""reference: python/paddle/dataset/image.py — numpy image transforms.

The reference shells out to cv2; these are pure-numpy equivalents
(bilinear resize, crops, flip, CHW conversion, simple_transform) so the
1.x reader pipelines work without OpenCV.
"""
from __future__ import annotations

import numpy as np

__all__ = ["resize_short", "center_crop", "random_crop",
           "left_right_flip", "to_chw", "simple_transform"]


def _resize_bilinear(img, h, w):
    """img [H, W, C] (or [H, W]) -> [h, w, ...] bilinear."""
    img = np.asarray(img)
    H, W = img.shape[:2]
    if (H, W) == (h, w):
        return img
    ys = (np.arange(h) + 0.5) * H / h - 0.5
    xs = (np.arange(w) + 0.5) * W / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, H - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, W - 1)
    y1 = np.clip(y0 + 1, 0, H - 1)
    x1 = np.clip(x0 + 1, 0, W - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None]
    wx = np.clip(xs - x0, 0, 1)[None, :]
    if img.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    a = img[y0][:, x0]
    b = img[y0][:, x1]
    c = img[y1][:, x0]
    d = img[y1][:, x1]
    top = a * (1 - wx) + b * wx
    bot = c * (1 - wx) + d * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(img.dtype) if np.issubdtype(img.dtype, np.integer) \
        else out


def resize_short(im, size):
    """Scale so the SHORTER edge equals ``size`` (image.py:resize_short)."""
    h, w = im.shape[:2]
    if h < w:
        return _resize_bilinear(im, size, int(round(w * size / h)))
    return _resize_bilinear(im, int(round(h * size / w)), size)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h0 = max((h - size) // 2, 0)
    w0 = max((w - size) // 2, 0)
    return im[h0:h0 + size, w0:w0 + size]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h0 = np.random.randint(0, max(h - size, 0) + 1)
    w0 = np.random.randint(0, max(w - size, 0) + 1)
    return im[h0:h0 + size, w0:w0 + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def to_chw(im, order=(2, 0, 1)):
    return im.transpose(order)


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """resize-short -> crop (+random flip in train) -> CHW -> -mean
    (image.py:simple_transform)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        if mean.ndim == 1 and im.ndim == 3:
            mean = mean[:, None, None]
        im -= mean
    return im
