"""reference: python/paddle/dataset/wmt14.py — (src, trg, trg_next)."""
from __future__ import annotations

__all__ = ["train", "test"]


def _reader(mode, dict_size):
    def reader():
        from ..text.datasets import WMT14
        ds = WMT14(mode=mode, dict_size=dict_size)
        for i in range(len(ds)):
            yield ds[i]
    return reader


def train(dict_size=30000):
    return _reader("train", dict_size)


def test(dict_size=30000):
    return _reader("test", dict_size)
