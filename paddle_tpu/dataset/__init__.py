"""1.x ``paddle.dataset`` namespace — reader-generator factories.

Reference parity: ``python/paddle/dataset/`` (mnist/cifar/uci_housing/
imdb/imikolov/movielens/conll05/wmt14/wmt16/voc2012/image/common).
Each module exposes the reference's ``train()``/``test()`` factories
returning zero-arg generators of sample tuples.

TPU-native/no-egress design: everything delegates to the class-style
datasets (``vision/datasets.py``, ``text/datasets.py``) which load a
local cache when present and otherwise synthesize deterministic samples
with the correct shapes/dtypes — the 1.x reader surface is an adapter,
not a second implementation.
"""
from . import common  # noqa: F401
from . import mnist  # noqa: F401
from . import cifar  # noqa: F401
from . import uci_housing  # noqa: F401
from . import imdb  # noqa: F401
from . import imikolov  # noqa: F401
from . import movielens  # noqa: F401
from . import conll05  # noqa: F401
from . import wmt14  # noqa: F401
from . import wmt16  # noqa: F401
from . import voc2012  # noqa: F401
from . import flowers  # noqa: F401
from . import image  # noqa: F401

__all__ = ["common", "mnist", "cifar", "uci_housing", "imdb",
           "imikolov", "movielens", "conll05", "wmt14", "wmt16",
           "voc2012", "flowers", "image"]
