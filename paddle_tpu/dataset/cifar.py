"""reference: python/paddle/dataset/cifar.py — yields
(image[3072] float32 in [0, 1], label int)."""
from __future__ import annotations

import numpy as np

__all__ = ["train10", "test10", "train100", "test100"]


def _reader(cls_name, mode):
    def reader():
        from ..vision import datasets as vds
        ds = getattr(vds, cls_name)(mode=mode)
        for i in range(len(ds)):
            img, label = ds[i]
            yield np.asarray(img, np.float32).reshape(-1), int(label)
    return reader


def train10():
    return _reader("Cifar10", "train")


def test10():
    return _reader("Cifar10", "test")


def train100():
    return _reader("Cifar100", "train")


def test100():
    return _reader("Cifar100", "test")
