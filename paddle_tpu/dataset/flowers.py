"""reference: python/paddle/dataset/flowers.py — (image, label)."""
from __future__ import annotations

__all__ = ["train", "test", "valid"]


def _reader(mode):
    def reader():
        from ..vision.datasets import Flowers
        ds = Flowers(mode=mode)
        for i in range(len(ds)):
            yield ds[i]
    return reader


def train(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader("train")


def test(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader("test")


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader("valid")
