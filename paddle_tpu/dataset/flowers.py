"""reference: python/paddle/dataset/flowers.py — (image, label)."""
from __future__ import annotations

__all__ = ["train", "test", "valid"]


def _reader(mode, mapper):
    def reader():
        from ..vision.datasets import Flowers
        ds = Flowers(mode=mode)
        for i in range(len(ds)):
            sample = ds[i]
            # the reference applies mapper per sample (typically the
            # dataset.image transforms)
            yield mapper(sample) if mapper is not None else sample
    return reader


def train(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader("train", mapper)


def test(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader("test", mapper)


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader("valid", mapper)
