"""reference: python/paddle/dataset/uci_housing.py — yields
(features[13] f32 normalized, price[1] f32)."""
from __future__ import annotations

__all__ = ["train", "test"]


def _reader(mode):
    def reader():
        from ..text.datasets import UCIHousing
        ds = UCIHousing(mode=mode)
        for i in range(len(ds)):
            feats, price = ds[i]
            yield feats, price
    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")
