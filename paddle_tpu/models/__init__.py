"""Model zoo: language models (GPT/BERT) + vision re-exports."""
from .gpt import (  # noqa: F401
    GPTModel, GPTBlock, GPTEmbeddings, GPTLMHead, GPTPretrainingCriterion,
    GPT_CONFIGS, gpt_pipe_model,
)
from .bert import (  # noqa: F401
    BertModel, BertForSequenceClassification, BertForMaskedLM,
    BertPretrainingCriterion, BERT_CONFIGS,
)
from ..vision.models import (  # noqa: F401
    LeNet, resnet18, resnet50, vgg16, mobilenet_v2,
)
